"""Eager mini-controller: the background cycle loop.

Parity surface: ``horovod/common/operations.cc``
(``BackgroundThreadLoop`` / ``RunLoopOnce`` / ``PerformOperation``) and
the coordination cycle of ``horovod/common/controller.cc``
(``ComputeResponseList``).  This is what lets each rank *enqueue eager
collectives in any order* — gradients materializing in different orders
across ranks — while every rank *executes* the identical agreed
sequence, which the XLA data plane (comm/eager.py) requires.

Division of labor:
- decision logic (queueing, readiness, fusion, caching, stall tracking)
  lives in the native core (``horovod_tpu.native`` — C++ when built,
  Python twin otherwise);
- this module owns the *cycle thread*, the *transport* of coordination
  blobs between ranks, and the *execution* of agreed responses on the
  XLA data plane, resolving per-op futures.

Transport: the reference gathers requests at rank 0 over MPI_Gatherv /
Gloo and broadcasts responses back.  Here the blobs ride the JAX
coordination-service KV store (``jax.distributed``) — the same service
that replaced the Gloo HTTP rendezvous — via per-cycle keys.  A
single-process world short-circuits the transport entirely.
"""

from __future__ import annotations

import base64
import collections
import itertools
import logging
import os
import queue
import threading
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import native
from ..native import wire
from ..comm import eager as eager_comm
from ..comm import packing as comm_packing
from ..comm.compression import NoneCompressor
from ..comm.packing import pack_flat, unpack_flat
from ..comm.reduce_ops import ReduceOp
from ..core import clock
from ..core import faults
from ..core import preempt
from ..core import retry as core_retry
from ..core.exceptions import HorovodInternalError, HvtpuMismatchError
from ..obs import anomaly
from ..obs import flight
from ..obs import metrics as obs_metrics
from ..obs import tracing

logger = logging.getLogger("horovod_tpu.eager")

# Controller telemetry (obs/metrics.py; catalog in docs/observability.md).
_M_CYCLES = obs_metrics.counter(
    "hvtpu_controller_cycles_total", "Coordination cycles run.")
_M_CYCLE_S = obs_metrics.histogram(
    "hvtpu_controller_cycle_seconds",
    "Coordination cycle duration (coalescing gate + drain + transport "
    "exchange; execution overlaps on the pipelined executor thread, "
    "inline only in manual/test mode).")
_M_QUEUE_DEPTH = obs_metrics.gauge(
    "hvtpu_controller_queue_depth",
    "Ops enqueued but not yet executed, sampled after each cycle.")
_M_NEGOTIATION_S = obs_metrics.histogram(
    "hvtpu_negotiation_seconds",
    "Enqueue-to-agreed-response latency through the controller.")
_M_CACHE_HITS = obs_metrics.counter(
    "hvtpu_controller_cache_hits_total",
    "Requests answered from the response cache (name+signature only "
    "on the wire).")
_M_CACHE_SIZE = obs_metrics.gauge(
    "hvtpu_controller_cache_size", "Live response-cache entries.")
_M_BYPASS = obs_metrics.counter(
    "hvtpu_controller_bypass_cycles_total",
    "Steady-state cycles negotiated via the compact cache-bit vector "
    "(no serialized requests on the wire).")
_M_RESYNC = obs_metrics.counter(
    "hvtpu_controller_resync_cycles_total",
    "Full-resync cycles (periodic cadence or coordinator-forced) that "
    "re-anchor the coordinator's message table on full entries.")
_M_PREDICTED = obs_metrics.counter(
    "hvtpu_controller_predicted_cycles_total",
    "Steady-state bypass cycles whose agreed schedule was predicted "
    "locally from the replicated response cache and executed without "
    "waiting for the coordinator round trip.")
_M_MISPREDICT = obs_metrics.counter(
    "hvtpu_controller_mispredicts_total",
    "Predicted schedules the coordinator did NOT confirm (the released "
    "schedule differed); every one forces immediate full negotiation "
    "and a cache-resync re-anchor — fail back to correct, never to "
    "fast.")
_M_MISMATCH = obs_metrics.counter(
    "hvtpu_controller_mismatch_errors_total",
    "Error responses for cross-rank tensor-metadata disagreement "
    "(mismatched type/red_op/dtype/shape/root for one tensor name), "
    "surfaced as HvtpuMismatchError on every member rank.")
_M_ARRIVAL_SKEW = obs_metrics.histogram(
    "hvtpu_collective_arrival_skew_seconds",
    "Per-collective spread between the first and last member rank's "
    "announcement reaching the coordinator (rank 0 only; straggler "
    "signal, see docs/observability.md).",
    buckets=(0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0))
_M_LAST_ARRIVER = obs_metrics.counter(
    "hvtpu_collective_last_arriver_total",
    "Times each rank was the LAST member to announce a collective "
    "(rank 0 only; labeled by the straggling rank).")
_M_FUSION_ZC = obs_metrics.counter(
    "hvtpu_fusion_zero_copy_ops_total",
    "Fused allreduce ops that rode the zero-copy fusion-buffer plane: "
    "payload bytes packed into the pooled exchange buffer at enqueue "
    "time (offsets fixed by the steady predicted schedule) and "
    "unpacked as lazy views — no drain-time staging copies.")
_M_FUSION_STAGED = obs_metrics.counter(
    "hvtpu_fusion_staged_copies_total",
    "Fused allreduce ops that took the drain-time staged-copy path "
    "(pack_flat concatenate + eager per-tensor unpack) because the "
    "drain was unpredicted, mispredicted, or the group was not "
    "prepack-eligible — fail back to correct, never to fast.")

#: Error-text marker the controllers (C++ and Python twin, byte-
#: identical) emit for cross-rank metadata disagreement; used to raise
#: the typed error instead of the generic internal one.
_MISMATCH_MARKER = "cross-rank tensor mismatch"

_RED_TO_WIRE = {
    ReduceOp.SUM: wire.RED_SUM,
    ReduceOp.AVERAGE: wire.RED_AVERAGE,
    ReduceOp.MIN: wire.RED_MIN,
    ReduceOp.MAX: wire.RED_MAX,
    ReduceOp.PRODUCT: wire.RED_PRODUCT,
    ReduceOp.ADASUM: wire.RED_ADASUM,
}
_WIRE_TO_RED = {v: k for k, v in _RED_TO_WIRE.items()}


def _apply_scale(t, factor: float):
    """Pre/postscale around the fused wire: one-pass Pallas scale
    kernel for floats (parity: ScaleBuffer cuda_kernels around the
    fusion buffer); int dtypes keep the legacy truncating-scale
    semantics."""
    if jnp.issubdtype(t.dtype, jnp.floating):
        from ..ops import fused_scale_cast

        return fused_scale_cast(t.reshape(-1), factor).reshape(t.shape)
    return t * jnp.asarray(factor, t.dtype)


class _GroupUnpack:
    """Shared deferred MemcpyOutFusionBuffer for one zero-copy fused
    group: the first consumer materializes EVERY piece through ONE
    cached jitted slice/reshape/cast program
    (comm/packing.group_unpack_program) — no eager per-tensor copy
    loop — then returns the pooled exchange buffer, which must stay
    untouched until the device consumed the wire result (CPU
    device_put may alias host memory)."""

    __slots__ = ("_lock", "_red", "_specs", "_pack", "_pool", "_psid",
                 "_pieces")

    def __init__(self, red, specs, pack, pool, psid):
        self._lock = threading.Lock()
        self._red = red
        self._specs = specs
        self._pack = pack
        self._pool = pool
        self._psid = psid
        self._pieces = None

    def piece(self, i: int):
        with self._lock:
            if self._pieces is None:
                fn = comm_packing.group_unpack_program(self._specs)
                pieces = fn(self._red)
                jax.block_until_ready(pieces)
                self._pieces = list(pieces)
                self._red = None
                pack, self._pack = self._pack, None
                if pack is not None:
                    self._pool.release(self._psid, pack)
            return self._pieces[i]


class _LazyPiece:
    """Lazy unpack view a zero-copy fused op's future resolves with;
    :meth:`OpFuture.result` materializes (and caches) the real array
    on first access, so the slice/reshape/cast runs in the consumer's
    program instead of the executor's drain path."""

    __slots__ = ("_group", "_index", "_postscale")

    def __init__(self, group: _GroupUnpack, index: int, postscale: float):
        self._group = group
        self._index = index
        self._postscale = postscale

    def materialize(self):
        out = self._group.piece(self._index)
        if self._postscale != 1.0:
            out = _apply_scale(out, self._postscale)
        return out


class OpFuture:
    """Completion future for one enqueued op (parity: the handle slots of
    horovod/torch/handle_manager.cc — done flag + result/exception)."""

    def __init__(self, name: str):
        self.name = name
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def set_result(self, value):
        self._result = value
        self._event.set()

    def set_error(self, err: BaseException):
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"collective '{self.name}' did not complete in {timeout}s"
            )
        if self._error is not None:
            raise self._error
        r = self._result
        if type(r) is _LazyPiece:
            r = r.materialize()
            self._result = r
        return r


# --------------------------------------------------------------------------
# transports
# --------------------------------------------------------------------------

class TransportClosed(Exception):
    """The transport was closed while a cycle was blocked on it —
    a clean shutdown signal, not a failure."""


class LocalTransport:
    """Single-process world: coordinator == the only member."""

    #: Capability flag EagerController.start() consults instead of an
    #: isinstance check, so injected transports (the fabric simulator's
    #: per-rank KV facade) can opt into the streamed plane.
    supports_streaming = False

    def exchange(self, ctrl, cycle: int, request_blob: bytes) -> bytes:
        ctrl.ingest(request_blob)
        return ctrl.compute_responses()

    def close(self):
        pass


class KVTransport:
    """Coordination blobs over the JAX coordination-service KV store
    (replaces MPI_Gatherv/MPI_Bcast of mpi_controller.cc; the store
    itself replaces the Gloo HTTP rendezvous of http_server.py)."""

    supports_streaming = True

    def __init__(self, rank: int, size: int, client=None,
                 timeout_s: float = 600.0, namespace: str = "hvt_eager",
                 poll_s: float = 1.0):
        if client is None:
            from jax._src import distributed as _jd

            client = _jd.global_state.client
            if client is None:
                raise RuntimeError(
                    "KVTransport requires jax.distributed to be initialized"
                )
        self._kv = client
        self.rank = rank
        self.size = size
        self.timeout_ms = int(timeout_s * 1000)
        self.ns = namespace
        # Blocking gets are chunked into short polls so close() can
        # unblock the cycle thread promptly at shutdown (the service
        # has no cancellable get).
        self.poll_s = poll_s
        self._closed = threading.Event()
        # Raw-bytes KV API (newer jaxlib): skips base64 entirely —
        # one less encode+decode per blob and 25% less wire payload.
        self._bytes = all(
            hasattr(client, m) for m in
            ("key_value_set_bytes", "blocking_key_value_get_bytes",
             "key_value_dir_get_bytes"))
        # One directory RPC gathers every posted request blob at the
        # coordinator instead of P sequential blocking gets.
        self._dir = self._bytes or hasattr(client, "key_value_dir_get")
        # Transient coordination blips (and injected kv.put faults)
        # retry with backoff instead of killing the cycle thread — the
        # negotiation channel must survive what the stall inspector's
        # channel already survives (core/retry.py ResilientKV).
        self._put_policy = core_retry.kv_policy()

    def _set(self, key: str, blob: bytes):
        def _put():
            if faults.ACTIVE and faults.inject("kv.put", detail=key):
                return  # dropped writes stay dropped (peer times out)
            if self._bytes:
                self._kv.key_value_set_bytes(key, blob)
            else:
                self._kv.key_value_set(key, base64.b64encode(blob).decode())

        core_retry.call(
            self._put_policy, _put,
            on_retry=lambda a, e: obs_metrics.counter(
                "hvtpu_kv_retries_total").inc(),
        )

    def _get(self, key: str, deadline_s: Optional[float] = None) -> bytes:
        deadline = clock.monotonic() + (
            self.timeout_ms / 1000.0 if deadline_s is None else deadline_s)
        poll_ms = max(1, int(min(self.poll_s,
                                 deadline_s if deadline_s else self.poll_s)
                             * 1000))
        while True:
            if self._closed.is_set():
                raise TransportClosed(key)
            try:
                if faults.ACTIVE and faults.inject("kv.get", detail=key):
                    # dropped read == not posted yet this poll
                    raise TimeoutError(f"{key} (dropped by fault injection)")
                if self._bytes:
                    return bytes(
                        self._kv.blocking_key_value_get_bytes(
                            key, poll_ms))
                val = self._kv.blocking_key_value_get(key, poll_ms)
                return base64.b64decode(val)
            except Exception as e:
                msg = str(e)
                if self._closed.is_set() or "CANCELLED" in msg:
                    # Service shut down under us — clean exit.
                    raise TransportClosed(key) from None
                retryable = (isinstance(e, TimeoutError)
                             or "DEADLINE_EXCEEDED" in msg
                             or "NOT_FOUND" in msg
                             # transient channel blips (incl. injected
                             # UNAVAILABLE faults) poll again under the
                             # same deadline instead of dying
                             or core_retry.kv_retryable(e))
                if not retryable:
                    raise
                if clock.monotonic() > deadline:
                    raise TimeoutError(
                        f"coordination key {key!r} not posted within "
                        f"{self.timeout_ms / 1000.0:.0f}s"
                    ) from None

    def _delete(self, key: str):
        try:
            self._kv.key_value_delete(key)
        except Exception:
            pass

    def _gather_requests(self, ctrl, cycle: int):
        """Coordinator-side gather of every rank's request blob for
        this cycle: ONE directory RPC per poll returns all posted
        blobs (vs P sequential blocking gets), with the blocking-get
        path as fallback for clients without dir-get."""
        prefix = f"{self.ns}/c{cycle}/"
        if not self._dir:
            for r in range(self.size):
                ctrl.ingest(self._get(f"{prefix}r{r}"))
            return
        want = {f"{prefix}r{r}": r for r in range(self.size)}
        got: Dict[str, bytes] = {}
        deadline = clock.monotonic() + self.timeout_ms / 1000.0
        sleep = 0.0
        while True:
            if self._closed.is_set():
                raise TransportClosed(prefix)
            try:
                if faults.ACTIVE and faults.inject("kv.get", detail=prefix):
                    entries = []  # dropped dir read: empty this poll
                else:
                    entries = (self._kv.key_value_dir_get_bytes(prefix)
                               if self._bytes
                               else self._kv.key_value_dir_get(prefix))
            except Exception:
                entries = []
            for k, v in entries:
                if k in want and k not in got:
                    got[k] = (bytes(v) if self._bytes
                              else base64.b64decode(v))
            if len(got) == len(want):
                break
            if clock.monotonic() > deadline:
                missing = sorted(
                    r for k, r in want.items() if k not in got)
                raise TimeoutError(
                    f"request blobs for cycle {cycle} not posted "
                    f"within {self.timeout_ms / 1000.0:.0f}s "
                    f"(missing ranks {missing})")
            # fast first polls for the common sub-ms skew, then back
            # off toward poll_s — a rank in a long compute step must
            # not be hammered with O(P x blob) directory re-fetches
            sleep = min(self.poll_s, sleep * 2 if sleep else 2e-4)
            clock.sleep(sleep)
        # deterministic ingest order (coordinator decisions must not
        # depend on arrival order)
        for r in range(self.size):
            ctrl.ingest(got[f"{prefix}r{r}"])

    def exchange(self, ctrl, cycle: int, request_blob: bytes) -> bytes:
        req_key = f"{self.ns}/c{cycle}/r{self.rank}"
        resp_key = f"{self.ns}/c{cycle}/resp"
        self._set(req_key, request_blob)
        if self.rank == 0:
            self._gather_requests(ctrl, cycle)
            resp = ctrl.compute_responses()
            self._set(resp_key, resp)
            # GC the previous cycle's keys in ONE directory delete —
            # safe because ingesting every rank's cycle-N blob proves
            # they all consumed cycle N-1.
            if cycle > 0:
                self._delete(f"{self.ns}/c{cycle - 1}/")
            return resp
        return self._get(resp_key)

    # ---- streamed (barrier-free) control plane -----------------------
    # The lockstep exchange() above is a full all-rank barrier per
    # cycle: EVERY rank posts and fetches EVERY cycle, so idle cycles
    # cost real KV round-trips and the slowest rank's cadence bounds
    # everyone (measured ~2/3 of the per-batch wall clock at P=4).
    # The streamed plane matches the reference's architecture instead:
    # workers post request blobs to a per-rank stream whenever they
    # drain work, the coordinator ingests them at its own cadence and
    # appends agreed ResponseLists to a response stream, and every
    # rank applies that stream in order (which is what keeps response
    # caches and fusion state bit-identical).  Idle ranks hold ONE
    # parked blocking get and post nothing.

    def post_request(self, idx: int, blob: bytes):
        self._set(f"{self.ns}/q/{self.rank}/{idx}", blob)

    def post_response(self, idx: int, blob: bytes):
        self._set(f"{self.ns}/resp/{idx}", blob)

    def fetch_response(self, idx: int) -> Optional[bytes]:
        """Next ResponseList in the stream; blocks in short chunks and
        returns None on an idle chunk so the caller can re-check stop
        conditions.  TransportClosed on close()."""
        try:
            return self._get(f"{self.ns}/resp/{idx}",
                             deadline_s=self.poll_s)
        except TimeoutError:
            return None

    def post_ack(self, idx: int):
        """Advertise the highest applied response index (GC input)."""
        self._set(f"{self.ns}/ack/{self.rank}", str(idx).encode())

    def poll_requests(self, next_idx: Dict[int, int]
                      ) -> List[Tuple[int, int, bytes]]:
        """Coordinator-side: newly posted request blobs in (rank,
        stream-index) order, consuming (deleting) each.  ``next_idx``
        tracks the per-rank read cursor and is updated in place.  One
        directory RPC on clients with dir-get; short per-rank probes
        otherwise (in-memory test KVs)."""
        prefix = f"{self.ns}/q/"
        found: Dict[Tuple[int, int], bytes] = {}
        if self._dir:
            try:
                if faults.ACTIVE and faults.inject("kv.get", detail=prefix):
                    entries = []
                else:
                    entries = (self._kv.key_value_dir_get_bytes(prefix)
                               if self._bytes
                               else self._kv.key_value_dir_get(prefix))
            except Exception:
                entries = []
            for k, v in entries:
                parts = str(k).rsplit("/", 2)
                try:
                    r, i = int(parts[-2]), int(parts[-1])
                except (ValueError, IndexError):
                    continue
                found[(r, i)] = (bytes(v) if self._bytes
                                 else base64.b64decode(v))
        else:
            for r in range(self.size):
                if r == self.rank:
                    continue
                i = next_idx.get(r, 0)
                while True:
                    try:
                        found[(r, i)] = self._get(
                            f"{prefix}{r}/{i}", deadline_s=0.01)
                    except TimeoutError:
                        break
                    i += 1
        out: List[Tuple[int, int, bytes]] = []
        for r in sorted({r for r, _ in found}):
            i = next_idx.get(r, 0)
            while (r, i) in found:
                out.append((r, i, found[(r, i)]))
                self._delete(f"{prefix}{r}/{i}")
                i += 1
            next_idx[r] = i
        return out

    def gc_responses(self, last_gc: int) -> int:
        """Delete response-stream entries every rank has acked past;
        returns the new GC floor.  Dir-get clients only (in-memory
        test KVs never ack)."""
        if not self._dir:
            return last_gc
        try:
            entries = (self._kv.key_value_dir_get_bytes(f"{self.ns}/ack/")
                       if self._bytes
                       else self._kv.key_value_dir_get(f"{self.ns}/ack/"))
        except Exception:
            return last_gc
        acks = []
        for _k, v in entries:
            try:
                acks.append(int(bytes(v).decode() if self._bytes
                                else base64.b64decode(v).decode()))
            except (ValueError, UnicodeDecodeError):
                continue
        if len(acks) < self.size - 1:
            return last_gc  # some rank has never acked yet
        floor = min(acks)
        for i in range(last_gc, floor):
            self._delete(f"{self.ns}/resp/{i}")
        return max(last_gc, floor)

    def close(self):
        self._closed.set()


# --------------------------------------------------------------------------
# controller
# --------------------------------------------------------------------------

class _PackSlot:
    """One op's learned place in a fused group: pack payload bytes for
    ``name`` at index ``index`` of the exchange buffer for ``gkey`` =
    (psid, agreed tensor-name order).  Learned by
    ``_maybe_learn_pack_plan`` from an executed fused group, consulted
    by ``_maybe_prepack`` on the enqueue path."""

    __slots__ = ("gkey", "index", "spec", "rop", "psid")

    def __init__(self, gkey, index, spec, rop, psid):
        self.gkey = gkey
        self.index = index
        self.spec = spec
        self.rop = rop
        self.psid = psid


class _Payload:
    __slots__ = ("seq", "name", "future", "tensor", "rop", "prescale",
                 "postscale", "compressor", "splits", "kind",
                 "process_set", "psid", "root_rank", "t_enqueue",
                 "prepacked")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class EagerController:
    """Cycle-loop driver around the native controller.

    One instance per process; started lazily on first async enqueue
    (parity: InitializeHorovodOnce starting BackgroundThreadLoop).
    """

    def __init__(self, rank: int, size: int, *,
                 cycle_time_ms: float = 1.0,
                 fusion_threshold: int = 64 << 20,
                 cache_capacity: int = 1024,
                 stall_warn_s: float = 60.0,
                 stall_abort_s: float = 0.0,
                 transport=None,
                 timeline=None,
                 autotuner=None,
                 process_sets: Optional[Dict[int, List[int]]] = None,
                 manual: bool = False):
        self.rank, self.size = rank, size
        # manual=True: no background thread; tests drive run_cycle_once.
        self.manual = manual
        self.cycle_time_s = cycle_time_ms / 1000.0
        self.stall_warn_s = stall_warn_s
        self.stall_abort_s = stall_abort_s
        self._ctrl = native.make_controller(
            rank, size, fusion_threshold, cache_capacity,
            stall_warn_s, stall_abort_s,
        )
        # Local mirror of process-set membership so the executor can
        # skip responses scoped to sets this rank is not part of.
        self._ps_ranks: Dict[int, List[int]] = {0: list(range(size))}
        if process_sets:
            for psid, ranks in process_sets.items():
                self._ps_ranks[psid] = sorted(ranks)
                if psid != 0:
                    self._ctrl.register_process_set(psid, list(ranks))
        self._transport = transport or (
            LocalTransport() if size == 1 else KVTransport(rank, size)
        )
        self._timeline = timeline
        self._autotuner = autotuner
        self._seq = itertools.count(1)
        self._noname: Dict[str, itertools.count] = {}
        self._group_ids = itertools.count(1)
        # Coalescing-gate state: enqueues not yet drained, and when the
        # most recent one landed (see run_cycle_once).
        self._undrained = 0  # hvtpulint: guarded-by(_lock, racy-read-ok)
        self._last_enqueue_t = 0.0  # hvtpulint: guarded-by(_lock)
        # Steady-state burst tracking: once the same burst size repeats
        # (the per-step DistributedOptimizer pattern), the gate exits
        # the moment the expected count lands instead of waiting out
        # the quiesce window.
        self._expected_burst = 0
        self._burst_stable = 0
        # RLock: grouped_enqueue holds it across validate+declare+member
        # enqueues (which lock individually) so no concurrent enqueue can
        # slip a colliding name in mid-group.
        self._lock = threading.RLock()
        self._payloads: Dict[int, _Payload] = {}  # hvtpulint: guarded-by(_lock)
        self._by_name: Dict[str, int] = {}  # hvtpulint: guarded-by(_lock)
        self._join_futures: List[OpFuture] = []  # hvtpulint: guarded-by(_lock)
        self._joined_local = False  # hvtpulint: guarded-by(_lock)
        self._cycle = 0
        self._stall_logged: set = set()
        self._stop = threading.Event()
        # Wakes the cycle loop the moment work arrives, so idle
        # backoff (see _loop) never delays a locally-enqueued op.
        self._wake = threading.Event()
        # set when a ResponseList carries shutdown=True (every rank
        # announced) — the coordinated-quiesce signal
        self._shutdown_seen = threading.Event()
        # how long stop() keeps serving peers while waiting for global
        # shutdown agreement (matches the transport's blocking-get
        # budget; a hard-crashed peer surfaces as an error there first)
        self.shutdown_linger_s = 600.0
        self._thread: Optional[threading.Thread] = None
        self._thread_error: Optional[BaseException] = None
        # Pipelined data plane: agreed ResponseLists are executed on a
        # dedicated FIFO thread so cycle N's XLA dispatch + fetch
        # overlaps cycle N+1's drain/exchange (the reference gets this
        # overlap from PerformOperation running off the coordination
        # path).  A single ordered queue preserves the deterministic
        # response order the compile caches and fusion groups rely on.
        self._exec_queue: Optional["queue.Queue"] = None
        self._exec_thread: Optional[threading.Thread] = None
        # Streamed control plane (multi-process KV transports; see
        # KVTransport's streamed section): drainer + fetcher threads
        # replace the lockstep cycle loop.
        self._stream = False
        self._fetch_thread: Optional[threading.Thread] = None
        self._req_idx = 0
        self._next_resp = 0
        self._post_needed = False     # join/shutdown/resync announcements
        self._next_req_idx: Dict[int, int] = {}   # rank0 read cursors
        self._resp_idx = 0            # rank0 response stream head
        self._resp_gc = 0
        self._svc_dirty = False
        self._last_tuned = (-1, -1)
        self._local_resp: "collections.deque" = collections.deque()
        self._local_resp_ev = threading.Event()
        # Steady-state schedule prediction (see _try_predict): names
        # enqueued since the last drain, names drained but not yet
        # scheduled onto the executor, and the FIFO of predicted-and-
        # executed bursts awaiting the coordinator's post-hoc
        # confirmation — each record {"hash", "responses", "names"}
        # holds the FNV-1a 64 of the predicted ResponseList blob (what
        # a fully-predicted burst confirms as on the wire), the
        # predicted Responses (what a partially-predicted burst streams
        # back as), and the tensor names (mispredict blast radius).
        self._cache_capacity = cache_capacity
        self._pending_buf: List[str] = []  # hvtpulint: guarded-by(_lock)
        self._unsched: set = set()  # hvtpulint: guarded-by(_lock)
        self._predicted: "collections.deque" = collections.deque()  # hvtpulint: guarded-by(_lock)
        # Names whose predicted execution already resolved their
        # futures when a reset/mispredict abandoned the confirmation:
        # late real responses for them tolerate the missing payload
        # instead of dying on protocol corruption.
        self._mispredict_names: set = set()  # hvtpulint: guarded-by(_lock)
        # bit-sets whose predicted schedule has been VERIFIED against
        # the real response stream once (see _try_predict), plus the
        # FIFO of first-occurrence observations awaiting verification
        self._verified_bits: set = set()  # hvtpulint: guarded-by(_lock)
        self._observe: "collections.deque" = collections.deque()  # hvtpulint: guarded-by(_lock)
        self._tuned_seen = False
        # Schedule prediction (see _try_predict) is ON by default
        # ("auto") since the coordinator gained atomic burst units
        # (wire v5): a rank's drained burst ingests as one indivisible
        # unit and the coordinator never forms a fusion group across a
        # burst boundary, so a peer splitting a burst under load can
        # no longer diverge the released schedule from the predicted
        # one — the release is simply HELD until the unit completes.
        # "0" disables the fast path entirely.
        self._predict_on = (
            os.environ.get("HVTPU_EAGER_PREDICT", "auto") != "0")
        # Atomic-burst drain cap: once the steady burst size is
        # established, drain exactly one burst per wire unit even when
        # the next step's enqueues already started queueing ("0"
        # restores uncapped drains).
        self._burst_cap_on = (
            os.environ.get("HVTPU_EAGER_BURST_CAP", "1") != "0")
        # Verbose prediction-abort / drain diagnostics to stderr.
        self._debug = bool(os.environ.get("HVTPU_EAGER_DEBUG"))
        # Frontend-declared burst size (see hint_burst): lets the gate
        # hold a drain for a burst whose boundary it could not learn
        # yet, consumed by the drain that covers it.
        self._burst_hint = 0  # hvtpulint: guarded-by(_lock)
        # Zero-copy fusion-buffer plane (docs/design.md "Zero-copy
        # fusion buffers"): once a steady predicted schedule has shown
        # the fused groupings, _maybe_learn_pack_plan records each
        # op's (group, index, spec) slot so enqueue packs payload bytes
        # straight into a pooled exchange buffer — no drain-time
        # concatenate.  "0" disables enqueue-time packing (the staged
        # copy path still runs, it is the always-correct fallback).
        self._zero_copy_on = (
            os.environ.get("HVTPU_FUSION_ZERO_COPY", "1") != "0")
        self._fusion_pool = comm_packing.FusionBufferPool()
        # name -> _PackSlot learned from executed fused groups.
        self._pack_plan: Optional[Dict[str, "_PackSlot"]] = None  # hvtpulint: guarded-by(_lock)
        # gkey -> byte-spec list for pool acquisition.
        self._pack_group_specs: Dict[tuple, list] = {}  # hvtpulint: guarded-by(_lock)
        # gkey -> partially/fully filled ExchangeBuffer awaiting drain.
        self._open_packs: Dict[tuple, "comm_packing.ExchangeBuffer"] = {}  # hvtpulint: guarded-by(_lock)

    # ---- lifecycle ----
    def start(self):
        if self.manual:
            return
        if self._thread is None:
            self._stream = (
                self.size > 1
                and getattr(self._transport, "supports_streaming", False)
                and os.environ.get("HVTPU_EAGER_STREAM", "1") != "0"
            )
            self._exec_queue = queue.Queue(maxsize=4)
            self._exec_thread = threading.Thread(
                target=self._exec_loop, name="hvt-eager-executor",
                daemon=True,
            )
            self._exec_thread.start()
            if self._stream:
                self._fetch_thread = threading.Thread(
                    target=self._fetch_loop, name="hvt-eager-fetcher",
                    daemon=True,
                )
                self._fetch_thread.start()
                self._thread = threading.Thread(
                    target=self._drain_loop, name="hvt-eager-controller",
                    daemon=True,
                )
            else:
                self._thread = threading.Thread(
                    target=self._loop, name="hvt-eager-controller",
                    daemon=True,
                )
            self._thread.start()
            obs_metrics.register_debug_provider(
                "controller", self.debug_state)

    def quiesce(self, timeout: float = 5.0) -> bool:
        """Wait until this rank has no queued or in-flight eager ops —
        the pre-drain-commit barrier for core/preempt.py: the drain
        commit must not race collectives still being negotiated or
        executed.  A predicted cycle still awaiting the coordinator's
        post-hoc confirmation also blocks idleness: the emergency
        commit must not checkpoint state an unconfirmed (possibly
        mispredicted) schedule produced.  If the confirmation does not
        arrive within ``timeout`` while everything else is idle, the
        predictor ROLLS BACK to full negotiation — abandon the
        outstanding confirmations, force a resync re-anchor — and the
        quiesce succeeds on re-verified ground.  Returns True when the
        controller went idle within ``timeout`` (immediately true when
        already idle)."""
        deadline = clock.monotonic() + timeout
        while True:
            with self._lock:
                busy = bool(self._payloads) or self._undrained != 0
                unconfirmed = bool(self._predicted)
                if not busy and not unconfirmed:
                    # Idle: return enqueue-time-packed exchange buffers
                    # to the pool so the emergency commit never
                    # snapshots around half-filled packs (the pack plan
                    # itself survives — it is re-filled next burst).
                    self._release_open_packs()
            if not busy and not unconfirmed:
                return True
            if clock.monotonic() >= deadline:
                rolled_back = 0
                with self._lock:
                    if (not self._payloads and self._undrained == 0
                            and self._predicted):
                        rolled_back = len(self._predicted)
                        self._reset_predict_state()
                        force = getattr(self._ctrl, "force_resync", None)
                        if force is not None:
                            force()
                        self._post_needed = True
                if rolled_back:
                    logger.warning(
                        "quiesce: %d predicted cycle(s) unconfirmed at "
                        "deadline; rolled back to full negotiation",
                        rolled_back)
                    self._wake.set()
                    return True
                return False
            self._wake.set()
            clock.sleep(0.01)

    def request_shutdown(self):
        """Announce this rank's shutdown in subsequent cycles WITHOUT
        stopping the cycle loop (non-blocking half of the coordinated
        shutdown; tests stopping several same-process controllers call
        this on all of them before stop() so none lingers)."""
        self._ctrl.set_shutdown()
        # streamed plane posts nothing while idle: flag the drainer so
        # the announcement rides an (otherwise empty) request blob
        self._post_needed = True
        self._wake.set()

    def stop(self):
        # Coordinated shutdown (parity: horovod_shutdown negotiating
        # DONE via the controller): announce, then KEEP CYCLING —
        # serving peers' coordination — until every rank announced
        # (the coordinator leaving early would strand ranks mid-op,
        # e.g. a process-set collective whose response is never
        # emitted).
        if (self.size > 1 and not self.manual
                and self._thread is not None and self._thread.is_alive()
                and self._thread_error is None):
            self._ctrl.set_shutdown()
            self._post_needed = True
            self._wake.set()
            # The lockstep path escapes a dead coordinator via the
            # transport's blocking-get timeout; the streamed fetcher
            # polls patiently forever, so bound the linger by the same
            # budget — agreement that hasn't arrived within the
            # transport timeout is not coming.
            linger = self.shutdown_linger_s
            t_ms = getattr(self._transport, "timeout_ms", None)
            if t_ms:
                linger = min(linger, t_ms / 1000.0)
            deadline = clock.monotonic() + linger
            while clock.monotonic() < deadline:
                if self._shutdown_seen.wait(timeout=0.1):
                    break
                # the cycle thread dying (stall abort, transport
                # timeout) means agreement can never arrive
                if (self._thread is None or not self._thread.is_alive()
                        or self._thread_error is not None):
                    break
        self._stop.set()
        self._wake.set()
        obs_metrics.unregister_debug_provider("controller")
        # Close the transport so a cycle thread blocked in a
        # coordination-service get unblocks promptly (TransportClosed).
        self._transport.close()
        thread_exited = True
        if self._thread is not None:
            self._thread.join(timeout=30)
            thread_exited = not self._thread.is_alive()
            self._thread = None
        if self._fetch_thread is not None:
            self._local_resp_ev.set()
            self._fetch_thread.join(timeout=30)
            thread_exited = (thread_exited
                             and not self._fetch_thread.is_alive())
            self._fetch_thread = None
        # Drain the executor AFTER the cycle thread stopped producing:
        # queued responses still execute (their futures resolve), then
        # the sentinel ends the thread.
        if self._exec_thread is not None:
            try:
                self._exec_queue.put_nowait(None)
            except queue.Full:
                pass  # executor is stuck mid-dispatch; join times out
            self._exec_thread.join(timeout=30)
            thread_exited = thread_exited and not self._exec_thread.is_alive()
            self._exec_thread = None
        # Fail anything still outstanding, like the reference's shutdown
        # path completing callbacks with an aborted status.
        with self._lock:
            payloads = list(self._payloads.values())
            self._payloads.clear()
            self._by_name.clear()
        for p in payloads:
            p.future.set_error(
                HorovodInternalError("controller shut down with pending ops")
            )
        if thread_exited:
            self._ctrl.close()
        else:
            # The cycle thread may still be blocked in a transport call
            # holding a reference to the native controller; leaking it
            # beats a use-after-free when the call finally returns.
            logger.warning(
                "controller cycle thread did not exit within 30s; "
                "leaking native controller handle"
            )

    # ---- enqueue API ----
    def _auto_name(self, kind: str) -> str:
        # Parity: mpi_ops.py's "allreduce.noname.<n>" counters — one
        # counter PER KIND so unnamed ops of different kinds pair up
        # across ranks by per-kind issuance count even when ranks
        # interleave kinds in different orders.
        ctr = self._noname.setdefault(kind, itertools.count(0))
        return f"{kind}.noname.{next(ctr)}"

    def enqueue(self, kind: str, tensor, *, name: Optional[str] = None,
                op: ReduceOp = ReduceOp.SUM, process_set=None,
                prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                compression=NoneCompressor, root_rank: int = -1,
                splits=None, group_id: int = -1) -> OpFuture:
        if self._thread_error is not None:
            raise HorovodInternalError(
                f"controller thread died: {self._thread_error!r}"
            )
        x = jnp.asarray(tensor)
        if faults.ACTIVE:
            # The ``collective.pre`` site fires HERE, at the issuance
            # boundary, for async ops: a delay lands before the
            # announcement reaches the coordinator, so it shows up as
            # arrival skew (the straggler signal obs/anomaly names
            # ranks from) instead of vanishing inside the joint
            # execution barrier.  The executor's dispatch suppresses
            # the second firing (comm/eager.controller_execution).
            x = faults.inject_tensor("collective.pre", x,
                                     pset=process_set, detail=kind)
        name = name or self._auto_name(kind)
        kind_to_type = {
            "allreduce": wire.ALLREDUCE,
            "allgather": wire.ALLGATHER,
            "broadcast": wire.BROADCAST,
            "alltoall": wire.ALLTOALL,
            "reducescatter": wire.REDUCESCATTER,
            "barrier": wire.BARRIER,
        }
        op_type = kind_to_type[kind]
        compressor = compression
        # The wire dtype — what the collective actually moves — is the
        # fusion/caching signature (fusion_buffer_manager.cc keys fusion
        # on the buffer dtype).
        wire_dtype_name = str(jnp.dtype(compressor.wire_dtype(x.dtype)))
        dtype_id = wire.DTYPE_IDS.get(wire_dtype_name,
                                      wire.DTYPE_IDS.get(str(x.dtype), 6))
        psid = 0
        if process_set is not None:
            psid = (process_set if isinstance(process_set, int)
                    else process_set.process_set_id)

        fut = OpFuture(name)
        payload = _Payload(
            seq=None, name=name, future=fut, tensor=x,
            rop=op, prescale=prescale_factor, postscale=postscale_factor,
            compressor=compressor, splits=splits, kind=kind,
            process_set=process_set, psid=psid, root_rank=root_rank,
            t_enqueue=clock.monotonic(),
        )
        with self._lock:
            seq = next(self._seq)
            payload.seq = seq
            ok = self._ctrl.enqueue(
                seq, name, op_type, _RED_TO_WIRE[op], dtype_id,
                tuple(int(d) for d in x.shape), psid, group_id, root_rank,
            )
            if not ok:
                fut.set_error(HorovodInternalError(
                    f"duplicate tensor name in queue: {name!r} "
                    "(parity: TensorQueue DUPLICATE_NAME_ERROR)"
                ))
                return fut
            self._payloads[seq] = payload
            self._by_name[name] = seq
            self._undrained += 1
            self._pending_buf.append(name)
            self._last_enqueue_t = clock.monotonic()
            # Zero-copy plane: when a learned pack plan covers this op,
            # copy its bytes into the pooled exchange buffer NOW — the
            # group's entire MemcpyInFusionBuffer happens at enqueue
            # time.  Plan-less (non-steady) calls return immediately
            # (the < 5µs guard in tests/test_eager_controller.py).
            self._maybe_prepack(payload)
            if self._timeline is not None:
                # Parity: timeline.cc NEGOTIATE_<OP> span from enqueue
                # until the agreed response arrives (execution phases
                # come from the data plane).  Inside the lock: the
                # cycle thread could otherwise end() before begin().
                self._timeline.begin(name, f"NEGOTIATE_{kind.upper()}")
            if tracing.ACTIVE:
                # Same inside-the-lock ordering argument as above.
                tracing.op_begin(name, kind)
        self._wake.set()
        self.start()
        return fut

    def grouped_enqueue(self, kind: str, tensors, names=None, **kw
                        ) -> List[OpFuture]:
        """Enqueue a set that must execute together (parity:
        hvd.grouped_allreduce via group_table.cc).

        Names are validated up front: a duplicate (within the group or
        against a pending op) fails the WHOLE group immediately, since a
        partially-enqueued group could never reach its declared quorum
        and would hang the surviving members until stall abort.
        """
        eff_names = [
            (names[i] if names else None) or self._auto_name(kind)
            for i in range(len(tensors))
        ]
        # Hold the (reentrant) lock across check + declare + enqueues so
        # a concurrent enqueue can't introduce a colliding name after
        # the check but before a member lands — that would strand a
        # partially-filled group below its declared quorum forever.
        with self._lock:
            dup = None
            seen = set()
            for n in eff_names:
                if n in seen or n in self._by_name:
                    dup = n
                    break
                seen.add(n)
            if dup is not None:
                futs = []
                for n in eff_names:
                    f = OpFuture(n)
                    f.set_error(HorovodInternalError(
                        f"duplicate tensor name in group: {dup!r} "
                        "(parity: TensorQueue DUPLICATE_NAME_ERROR)"
                    ))
                    futs.append(f)
                return futs
            gid = next(self._group_ids)
            self._ctrl.declare_group(gid, len(tensors))
            futures = [
                self.enqueue(kind, t, name=n, group_id=gid, **kw)
                for t, n in zip(tensors, eff_names)
            ]
        return futures

    def register_process_set(self, psid: int, ranks: List[int]):
        """Mirror a newly-added process set into the coordination core
        (parity: ProcessSetTable additions reaching the controller)."""
        self._ps_ranks[psid] = sorted(ranks)
        self._ctrl.register_process_set(psid, list(ranks))

    def join(self) -> OpFuture:
        """Parity: hvd.join / EnqueueJoin — resolves with the last rank
        to join once every rank has.  While joined, this rank keeps
        cycling and contributes ZEROS to collectives the remaining
        ranks run (JoinOp semantics), so uneven final batches don't
        stall them.
        """
        fut = OpFuture("join")
        with self._lock:
            self._join_futures.append(fut)
            self._joined_local = True
        self._ctrl.set_joined()
        # the join announcement must go out even with an empty queue
        self._post_needed = True
        self._wake.set()
        self.start()
        return fut

    # ---- cycle loop ----
    def _loop(self):
        # Parity: BackgroundThreadLoop — run RunLoopOnce every cycle_time.
        # This thread's dispatches execute an already-negotiated
        # schedule with its own stall inspection — exempt them from the
        # sync path's pre-dispatch rendezvous (comm/stall.py).
        from ..comm import stall as sync_stall

        sync_stall.bypass_thread()
        # Idle backoff: each cycle is a full transport barrier (at
        # P>1, KV RPCs on every rank), so empty cycles are not free —
        # stretch the cadence up to 4x cycle_time while nothing is
        # happening.  A local enqueue snaps the loop awake via _wake;
        # a REMOTE rank's op waits at most the backed-off cadence
        # (bounded at 4 ms by default) for this rank's next exchange.
        idle_cycles = 0
        while not self._stop.is_set():
            t0 = clock.monotonic()
            try:
                active = self.run_cycle_once()
            except TransportClosed:
                # Clean shutdown while blocked on the wire; stop() fails
                # any still-pending futures.
                break
            except BaseException as e:  # noqa: BLE001 — must fail futures
                self._thread_error = e
                logger.exception("eager controller cycle failed")
                with self._lock:
                    payloads = list(self._payloads.values())
                    self._payloads.clear()
                    self._by_name.clear()
                for p in payloads:
                    p.future.set_error(HorovodInternalError(str(e)))
                return
            if self._shutdown_seen.is_set():
                # every rank announced shutdown: global quiesce
                return
            idle_cycles = 0 if active else min(idle_cycles + 1, 3)
            if active:
                elapsed = clock.monotonic() - t0
                sleep = self.cycle_time_s - elapsed
            else:
                # Empty cycles are not free: each is a full KV
                # transport barrier.  The backoff must be a FLOOR, not
                # a target minus elapsed — when the exchange itself is
                # slower than the cadence target (loaded host, remote
                # coordinator) the subtraction goes negative and the
                # loop spins back-to-back empty exchanges, starving
                # the data-plane executor of CPU.  A local enqueue
                # still snaps the wait via _wake; a remote rank's op
                # waits at most this backed-off cadence.
                sleep = self.cycle_time_s * (1 + idle_cycles)
            if sleep > 0:
                self._wake.wait(sleep)
            self._wake.clear()

    def _exec_loop(self):
        """Pipelined execution: dequeue agreed ResponseLists in cycle
        order and run the XLA data plane, overlapping the cycle
        thread's next drain/exchange.  Errors here fail all pending
        futures and stop the controller, mirroring _loop's error
        path."""
        from ..comm import stall as sync_stall

        sync_stall.bypass_thread()
        while True:
            item = self._exec_queue.get()
            if item is None:
                return
            rl, finished = item
            try:
                self._execute(rl, finished)
            except BaseException as e:  # noqa: BLE001 — must fail futures
                self._fail_all(e, "eager executor failed")
                return

    def _fail_all(self, e: BaseException, what: str):
        """Common control-plane death: record the error, fail every
        pending future, and unwedge the other threads."""
        self._thread_error = e
        logger.exception(what)
        with self._lock:
            payloads = list(self._payloads.values())
            self._payloads.clear()
            self._by_name.clear()
            self._pending_buf = []
            self._unsched.clear()
            self._predicted.clear()
            self._observe.clear()
            self._verified_bits.clear()
            self._mispredict_names.clear()
            self._release_open_packs()
            self._pack_plan = None
            self._pack_group_specs.clear()
        for p in payloads:
            p.future.set_error(HorovodInternalError(str(e)))
        self._stop.set()
        self._wake.set()
        self._local_resp_ev.set()

    def _reset_predict_state(self):  # hvtpulint: requires(_lock)
        """Forget everything the schedule predictor has learned —
        called (under ``_lock``) on membership change, error
        responses, coordinator-forced resync, mispredict, and quiesce
        rollback.  Resets the burst gate's steady state ITSELF
        (``_expected_burst``), not just the stability counter: after
        an elastic resize or a mismatch error the old burst size is
        exactly the wrong thing to keep gating (and predicting) on.
        Outstanding predicted bursts are abandoned; their names move
        to the tolerate set so late real responses for them don't
        read as protocol corruption."""
        self._expected_burst = 0
        self._burst_stable = 0
        self._verified_bits.clear()
        self._observe.clear()
        for rec in self._predicted:
            self._mispredict_names.update(rec["names"])
        self._predicted.clear()
        # The pack plan was learned FROM the schedule being forgotten:
        # drop it (and return any half-filled exchange buffers) so
        # enqueue-time packing stops until a steady schedule re-proves
        # the groupings.  Already-prepacked payloads simply mismatch
        # their (cleared) open pack at drain and take the staged path.
        self._release_open_packs()
        self._pack_plan = None
        self._pack_group_specs.clear()

    def _release_open_packs(self):  # hvtpulint: requires(_lock)
        """Return every open (partially filled) exchange buffer to the
        pool — quiesce/reset/teardown hygiene for the zero-copy
        plane."""
        for (psid, _names), xb in self._open_packs.items():
            self._fusion_pool.release(psid, xb)
        self._open_packs.clear()

    def _maybe_prepack(self, p: _Payload):  # hvtpulint: requires(_lock)
        """Enqueue-time MemcpyInFusionBuffer: when the learned pack
        plan has a slot for this op, copy its bytes straight into the
        group's pooled exchange buffer.  EVERY check degrades to a
        silent no-op — the drain-time staged path remains the source
        of truth (fail back to correct, never to fast).  First line is
        the whole cost on the non-steady path (plan None)."""
        plan = self._pack_plan
        if plan is None:
            return
        slot = plan.get(p.name)
        if slot is None:
            return
        if (p.kind != "allreduce" or p.compressor is not NoneCompressor
                or p.prescale != 1.0 or p.rop != slot.rop
                or p.psid != slot.psid):
            return
        arr = np.asarray(p.tensor)
        shape, dtype, _nbytes = slot.spec
        if tuple(arr.shape) != shape or arr.dtype != dtype:
            return
        pack = self._open_packs.get(slot.gkey)
        if pack is None:
            specs = self._pack_group_specs.get(slot.gkey)
            if specs is None:
                return
            pack = self._fusion_pool.acquire(slot.psid, specs)
            self._open_packs[slot.gkey] = pack
        if pack.write(slot.index, arr):
            p.prepacked = slot.gkey

    def _maybe_learn_pack_plan(self, rs, payloads):
        """Record the fused grouping an executed (staged-path) group
        proves, so the NEXT burst's enqueues can pack at enqueue time.
        Only steady predicted schedules qualify (``_burst_stable`` —
        the same stability bar as ``_try_predict``), and only plain
        groups (no compression, no prescale, uniform wire dtype):
        everything else keeps the staged path forever."""
        if not self._zero_copy_on or self._burst_stable < 2:
            return
        if any(p.compressor is not NoneCompressor or p.prescale != 1.0
               or p.seq == -1 for p in payloads):
            return
        dtype = payloads[0].tensor.dtype
        if any(p.tensor.dtype != dtype for p in payloads):
            return
        psid = payloads[0].psid
        gkey = (psid, tuple(rs.tensor_names))
        specs = [(tuple(p.tensor.shape), np.dtype(p.tensor.dtype),
                  int(p.tensor.nbytes)) for p in payloads]
        with self._lock:
            if self._pack_plan is None:
                self._pack_plan = {}
            for i, p in enumerate(payloads):
                self._pack_plan[p.name] = _PackSlot(
                    gkey=gkey, index=i, spec=specs[i], rop=p.rop,
                    psid=psid)
            self._pack_group_specs[gkey] = specs

    def _on_mispredict(self, why: str):  # hvtpulint: requires(_lock)
        """A predicted-and-executed schedule the coordinator did NOT
        confirm: fail back to correct, never to fast (callers hold
        ``_lock``).  Forces the next drain to be a full-entry resync
        re-anchor, drops the comm layer's memoized routing plans (they
        may hold artifacts jitted for the mispredicted grouping), and
        resets the predictor so the pattern must re-verify from
        scratch."""
        _M_MISPREDICT.inc()
        logger.error(
            "schedule mispredict (%s): forcing full negotiation + "
            "cache-resync re-anchor", why)
        if tracing.ACTIVE:
            tracing.instant("mispredict", why=why)
        if flight.ACTIVE:
            flight.note("mispredict", why=why)
        self._reset_predict_state()
        force = getattr(self._ctrl, "force_resync", None)
        if force is not None:
            force()
        self._post_needed = True
        self._wake.set()
        try:
            eager_comm.invalidate_routing_plans()
        except Exception:  # pragma: no cover — uninitialized worlds
            pass

    # ---- streamed control plane (multi-process KV transports) ----
    # Three threads instead of one lockstep cycle: the DRAINER gates
    # and posts this rank's request blobs (and, on rank 0, ingests
    # everyone's streams and appends agreed ResponseLists to the
    # response stream); the FETCHER applies the response stream in
    # order (identical order on every rank = bit-identical caches and
    # fusion state) and hands executions to the EXECUTOR.  No step in
    # this plane is an all-rank barrier: idle ranks hold one parked
    # blocking get and post nothing, and a busy rank's negotiation
    # overlaps its own (and everyone else's) data-plane execution.

    def _drain_loop(self):
        from ..comm import stall as sync_stall

        sync_stall.bypass_thread()
        # stall inspection is time-based here (the lockstep path keys
        # it to cycle counts); tight stall configs tighten the cadence
        limits = [s for s in (self.stall_warn_s, self.stall_abort_s)
                  if s and s > 0 and s != float("inf")]
        stall_every = min([2.0] + [max(0.05, s / 2) for s in limits])
        next_stall = clock.monotonic() + stall_every
        idle = 0
        while not self._stop.is_set():
            active = False
            try:
                if self._undrained or self._post_needed:
                    active = self._drain_once()
                if self.rank == 0:
                    active = self._service_once() or active
                if clock.monotonic() >= next_stall:
                    next_stall = clock.monotonic() + stall_every
                    self._inspect_stalls()
            except TransportClosed:
                break
            except BaseException as e:  # noqa: BLE001 — must fail futures
                self._fail_all(e, "eager controller drain loop failed")
                return
            if self._shutdown_seen.is_set():
                return
            idle = 0 if active else min(idle + 1, 6)
            if not active:
                # rank 0 keeps a polling cadence (remote ranks' blobs
                # arrive unannounced); workers park on _wake — their
                # responses arrive via the fetcher's blocking get.
                cap = (self.cycle_time_s * (1 + idle) if self.rank == 0
                       else 0.25)
                self._wake.wait(min(cap, stall_every))
                self._wake.clear()

    def _drain_once(self) -> bool:
        """Gate, drain and post ONE request blob (rank 0 ingests its
        own blob directly — no KV round trip for the coordinator's own
        ops); in steady state the agreed schedule is predicted and
        executed before the blob even leaves this host, and the blob
        itself goes out carrying the PREDICTED confirmation flag
        instead of waiting on a response round trip."""
        t0 = clock.monotonic()
        self._gate_burst()
        # Atomic-burst drain cap: with an established steady burst,
        # drain exactly one burst per wire unit — enqueues of the NEXT
        # step that raced in during the gate stay queued for their own
        # unit instead of riding (and destabilizing) this one.
        limit = (self._expected_burst
                 if self._burst_cap_on and self._burst_stable >= 2
                 else 0)
        with self._lock:
            drained = self._undrained
            post_needed = self._post_needed
            if drained == 0 and not post_needed:
                return False
            take = min(drained, limit) if limit else drained
            self._undrained -= take
            self._post_needed = False
            names = self._pending_buf[:take]
            del self._pending_buf[:take]
            req = self._ctrl.drain_requests(limit)
        parsed = None
        if take:
            parsed = self._note_drained(take, req)
        if parsed is not None and self._try_predict(parsed, names):
            # Executed locally already: the blob becomes a compact
            # post-hoc confirmation (flags bit flipped in place) —
            # the coordinator matches it against its own release and
            # answers with a confirm hash, not a ResponseList.
            req = wire.mark_predicted(req)
            names = []
        if names:
            with self._lock:
                self._unsched.update(names)
        if self.rank == 0:
            self._ctrl.ingest(req)
            self._svc_dirty = True
        else:
            self._transport.post_request(self._req_idx, req)
            self._req_idx += 1
        if take < drained:
            self._wake.set()  # capped remainder drains next pass
        _M_CYCLES.inc()
        _M_CYCLE_S.observe(clock.monotonic() - t0)
        return True

    def _try_predict(self, parsed: wire.RequestList,
                     names: List[str]) -> bool:
        """Steady-state fast path: a pure bypass drain whose agreed
        ResponseList is a deterministic function of state replicated
        on every rank — the response cache (bit-identical by
        apply-order construction) and the fusion threshold — executes
        IMMEDIATELY; the real response is verified and skipped when it
        streams in.  Gating keeps the determinism argument airtight:

        - bypass blob only (all cache hits, no join/shutdown flags);
        - never-tuned fusion threshold and no autotuner (a tuned
          threshold could reach ranks at different times and change
          the fusion split);
        - cache below capacity (no eviction has ever occurred, so bit
          ids can never have been reused while our response stream
          lags the coordinator's);
        - every predicted response is an additive allreduce
          (Sum/Average, non-int8): remote joins zero-contribute
          without error responses, so membership changes we have not
          yet observed cannot change the response content;
        - nothing drained earlier is still awaiting its response
          (_unsched empty), so predicted executions cannot reorder
          against in-flight negotiated ones;
        - and the bit-set's EXACT predicted schedule has been
          verified against the real response stream once before
          (first occurrence of any pattern is observed, not
          predicted): the world has demonstrated that it releases
          exactly this fused response for this set.

        A rank that predicts and a rank that repeats the verified
        pattern execute the same collectives in the same order, and
        the coordinator's atomic burst units (wire v5) guarantee the
        release can never fuse across a burst boundary — a peer whose
        gate splits a burst merely HOLDS the release until the unit
        completes.  A misprediction therefore requires a peer
        DEVIATING from a pattern it just established without a cache
        miss — the strict-SPMD contract the sync API already imposes —
        and even then the coordinator's refusal to confirm forces an
        immediate full negotiation + resync re-anchor (see
        _fetch_loop): fail back to correct, never to fast.
        Default-on ("auto"); ``HVTPU_EAGER_PREDICT=0`` disables the
        fast path entirely."""
        if not (self._stream and self._predict_on
                and parsed.cache_bypass):
            return False
        if preempt.pending():
            # A coordinated drain is in flight: no NEW speculation —
            # everything from here to the emergency commit runs fully
            # negotiated (quiesce handles predictions already made).
            return False
        if self._autotuner is not None or self._tuned_seen:
            return False
        if self._burst_stable < 2:
            return False
        try:
            if self._ctrl.cache_size >= self._cache_capacity:
                return False
        except Exception:
            return False
        predict = getattr(self._ctrl, "predict_responses", None)
        if predict is None:
            return False
        bits = wire.words_to_bits(parsed.cache_bits)
        blob = predict(bits)
        if blob is None:
            return False
        rl = wire.parse_response_list(blob)
        int8 = wire.DTYPE_IDS["int8"]
        for rs in rl.responses:
            if (rs.type != wire.ALLREDUCE
                    or rs.red_op not in (wire.RED_SUM, wire.RED_AVERAGE)
                    or rs.dtype == int8 or rs.error):
                return False
        got = [n for rs in rl.responses for n in rs.tensor_names]
        if sorted(got) != sorted(names):
            if self._debug:
                logger.error(
                    "predict abort: schedule covers %r, drain holds %r",
                    sorted(got), sorted(names))
            return False
        key = frozenset(bits)
        with self._lock:
            if self._unsched:
                return False
            if key not in self._verified_bits:
                # first occurrence: observe the real stream instead,
                # verifying that the world releases exactly this
                # schedule (bounded FIFO: stale observations age out)
                self._observe.append(
                    [key, list(rl.responses), 0])
                while len(self._observe) > 8:
                    self._observe.popleft()
                return False
            # One record per predicted burst: the hash is what a
            # fully-predicted release confirms as (the coordinator
            # hashes the bare fused ResponseList of the burst's
            # component — byte-identical to `blob` by construction);
            # the responses are what a PARTIALLY-predicted release
            # (some member observed instead) streams back as.
            self._predicted.append({
                "hash": wire.fnv1a64(blob),
                "responses": list(rl.responses),
                "names": list(got),
            })
        if tracing.ACTIVE:
            for n in got:
                tracing.op_phase(n, tracing.PREDICT)
        # retire in-flight NOW: the futures resolve on execution, and
        # the next step re-enqueues the same names before the real
        # response streams in
        self._ctrl.finish(got)
        self._dispatch_execution(rl, [])
        _M_PREDICTED.inc()
        return True

    def _drain_arrival_skew(self):
        """Coordinator only: feed per-op arrival spreads recorded in
        the message table into the straggler metrics (and, when
        tracing, an ``arrival_skew`` instant).  The native twin does
        not record them — getattr-guarded, metric simply stays 0."""
        if self.rank != 0:
            return
        take = getattr(self._ctrl, "take_arrival_skew", None)
        if take is None:
            return
        for name, skew, last in take():
            _M_ARRIVAL_SKEW.observe(skew)
            _M_LAST_ARRIVER.inc(rank=str(last))
            if tracing.ACTIVE:
                tracing.instant("arrival_skew", tensor=name,
                                skew_s=skew, last_rank=last)
            # straggler detection: the same drained sample feeds the
            # online anomaly plane, which names the offending rank by
            # joining these last-arriver observations
            if anomaly.ACTIVE:
                anomaly.on_arrival_skew(name, skew, last)

    def _service_once(self) -> bool:
        """Rank-0 coordination service: ingest newly streamed request
        blobs, compute responses, append non-trivial ResponseLists to
        the response stream (and feed our own fetcher in-process)."""
        got = self._transport.poll_requests(self._next_req_idx)
        for _r, _i, blob in got:
            self._ctrl.ingest(blob)
        if not got and not self._svc_dirty:
            return False
        self._svc_dirty = False
        resp = self._ctrl.compute_responses()
        self._drain_arrival_skew()
        rl = wire.parse_response_list(resp)
        tuned = (rl.tuned_fusion_threshold, rl.tuned_cycle_time_us)
        # confirm_hashes are non-trivial: every predictor's FIFO is
        # waiting on them (an unposted confirmation would read as a
        # mispredict-shaped stall at quiesce time)
        trivial = (not rl.responses and not rl.confirm_hashes
                   and rl.join_last_rank < 0
                   and not rl.shutdown and not rl.cache_resync_needed
                   and tuned == self._last_tuned)
        if not trivial:
            self._last_tuned = tuned
            self._transport.post_response(self._resp_idx, resp)
            self._resp_idx += 1
            self._local_resp.append(resp)
            self._local_resp_ev.set()
            if self._resp_idx % 64 == 0:
                self._resp_gc = self._transport.gc_responses(self._resp_gc)
        return bool(got) or not trivial

    def _fetch_loop(self):
        """Apply the response stream in order; every rank sees the
        identical sequence, which is what keeps response-cache bit ids
        and fusion state bit-identical across ranks."""
        from ..comm import stall as sync_stall

        sync_stall.bypass_thread()
        while not self._stop.is_set():
            try:
                if not self._fetch_once():
                    continue
            except TransportClosed:
                break
            except BaseException as e:  # noqa: BLE001
                self._fail_all(e, "eager controller fetch loop failed")
                return
            if self._shutdown_seen.is_set():
                return

    def _fetch_once(self, wait_s: float = 0.25) -> bool:
        """One streamed-plane fetch step: take the next response blob
        (rank 0 from its in-process feed, other ranks from the KV
        response stream) and apply it.  Returns True when a blob was
        applied, False when none arrived within ``wait_s``.  Factored
        out of :meth:`_fetch_loop` so the fabric simulator can pump the
        response plane one application at a time with no fetcher
        thread (``wait_s=0``); exceptions propagate to the caller."""
        if self.rank == 0:
            if not self._local_resp:
                self._local_resp_ev.wait(wait_s)
                self._local_resp_ev.clear()
                if not self._local_resp:
                    return False
            blob = self._local_resp.popleft()
        else:
            blob = self._transport.fetch_response(self._next_resp)
            if blob is None:
                return False
        self._apply_response_blob(blob)
        return True

    def _apply_response_blob(self, blob: bytes) -> None:
        finished = self._ctrl.apply_responses(blob)
        rl = wire.parse_response_list(blob)
        if rl.cache_resync_needed:
            # re-announce in-flight ops next drain (see the
            # controller's resync-flush handling)
            self._post_needed = True
            self._wake.set()
        with self._lock:
            # Post-hoc confirmations first: the coordinator
            # emits burst components in every rank's drain
            # order, so each hash must retire the OLDEST
            # outstanding prediction.  A hash matching nothing
            # in the FIFO belongs to a component this rank is
            # not a member of (or is stale after a reset) —
            # ignored; a hash matching a LATER record means
            # the head burst was released differently:
            # mispredict.
            for h in rl.confirm_hashes:
                if (self._predicted
                        and h == self._predicted[0]["hash"]):
                    rec = self._predicted.popleft()
                    if tracing.ACTIVE:
                        # confirmation instant: the predicted
                        # burst's PREDICT spans were real —
                        # hvtputrace overlap attributes them
                        # as coordination, not compute
                        tracing.instant(
                            "predict_confirm", how="hash",
                            names=list(rec["names"]))
                elif any(h == rec["hash"]
                         for rec in self._predicted):
                    self._on_mispredict(
                        "confirmation skipped the oldest "
                        "outstanding prediction (hash "
                        f"{h:#018x} matched a later burst)")
            # verify-and-skip responses already executed from
            # a predicted schedule (FIFO: the response stream
            # and the prediction order are both drain-
            # ordered); every other response marks its tensors
            # as scheduled
            keep = []
            for rs in rl.responses:
                rec = (self._predicted[0] if self._predicted
                       else None)
                if (rec is not None and rec["responses"]
                        and rs == rec["responses"][0]):
                    # a partially-predicted burst (some member
                    # observed instead, so no suppression)
                    # streams real responses: byte-verify
                    # against the prediction, skip re-execution
                    rec["responses"].pop(0)
                    if not rec["responses"]:
                        self._predicted.popleft()
                        if tracing.ACTIVE:
                            # stream byte-verify drained the
                            # whole predicted burst
                            tracing.instant(
                                "predict_confirm",
                                how="byte-verify",
                                names=list(rec["names"]))
                    continue
                if rec is not None and set(
                        rs.tensor_names) & set(rec["names"]):
                    # shares tensors with the oldest predicted
                    # burst but differs from its schedule: the
                    # coordinator released something else
                    self._on_mispredict(
                        "released schedule diverged from the "
                        f"predicted one for {rs.tensor_names}")
                for n in rs.tensor_names:
                    self._unsched.discard(n)
                if self._observe:
                    # first-occurrence verification: the real
                    # stream must emit EXACTLY the predicted
                    # schedule before a bit-set may predict
                    ob = self._observe[0]
                    if rs in ob[1]:
                        ob[2] += 1
                        if ob[2] == len(ob[1]):
                            self._verified_bits.add(ob[0])
                            self._observe.popleft()
                    else:
                        ob_names = {n for pr in ob[1]
                                    for n in pr.tensor_names}
                        if ob_names.intersection(rs.tensor_names):
                            # shares tensors but differs: the
                            # world disagrees — never verify
                            self._observe.popleft()
                keep.append(rs)
            rl.responses = keep
        self._dispatch_execution(rl, finished)
        self._next_resp += 1
        if self.rank != 0 and self._next_resp % 64 == 0:
            try:
                self._transport.post_ack(self._next_resp - 1)
            except Exception:
                pass

    # ---- shared negotiation plumbing ----
    def hint_burst(self, n: int):
        """Frontend burst declaration: the enqueue burst now streaming
        in will contain ``n`` ops (the torch ``DistributedOptimizer``
        knows its per-step gradient count; hooks re-arm the hint every
        backward).  The gate then holds the drain for the whole hinted
        burst instead of guessing the boundary from quiet gaps — on a
        loaded host the gap between two backward hooks can exceed any
        reasonable quiesce window, and every mis-split burst packs
        novel fusion-buffer shapes (fresh XLA compiles) AND denies the
        schedule predictor a stable pattern.  Purely a latency gate: a
        wrong hint costs at most the gate deadline, never correctness.
        Consumed by the next drain that covers it."""
        with self._lock:
            self._burst_hint = max(0, int(n))

    def _gate_burst(self):
        """Fusion-coalescing gate (the reference gets this from
        cycle_time batching: ops enqueued within one cycle fuse into
        one response).  While a burst of enqueues is still streaming
        in, wait for a sub-cycle quiet gap before draining so the
        WHOLE burst negotiates as one deterministic fusion group.
        This matters doubly on XLA: a split burst (e.g. 6+2 instead
        of 8) packs differently-shaped fusion buffers, and every
        novel shape combo pays a fresh compile — measured 80-90 ms
        spikes vs 2-4 ms steady-state for the same payload.
        Deterministic groups keep the pack/unpack compile caches hot.
        quiesce = one full cycle of quiet; deadline bounds the added
        negotiation latency for a genuinely continuous stream.

        Steady-state fast exit: when the burst size has repeated for
        >= 2 drains (the per-step optimizer pattern), exit the moment
        the expected count lands — the quiesce wait exists to find
        the burst boundary, and a stable history IS that boundary.
        An unstable stream falls back to quiesce gating, so transient
        workload changes cost at most a couple of odd-shaped
        (recompiling) drains before re-stabilizing."""
        quiesce = self.cycle_time_s
        span = 8 * self.cycle_time_s
        if self._stream:
            # The lockstep path's exchange barrier (~ms of KV RPCs)
            # paced the drain for free; the streamed drainer would
            # otherwise split a burst whose per-op enqueue cost (torch
            # DLPack adapter under load) exceeds one cycle_time — and
            # every split packs novel fusion-buffer shapes, paying
            # 80-90 ms XLA recompiles.  Widen the quiet gap and the
            # burst deadline to frontend-scale latencies.
            quiesce = max(quiesce, 0.004)
            span = max(span, 0.024)
        with self._lock:
            hint = self._burst_hint
        expected = (self._expected_burst
                    if self._burst_stable >= 2 else hint)
        # Steady mode waits for the WHOLE expected burst (a split
        # burst changes the negotiated fusion groups — recompiles at
        # best, and at worst diverges a predicted schedule from the
        # real one), with a long deadline so only a genuine workload
        # change (which then resets stability) can split it.  A
        # frontend-hinted burst gets the longest hold: the hint is
        # declared intent, and the hooks feeding it can be paced by a
        # slow backward under load.
        deadline = clock.monotonic() + (
            max(span, 0.25) if hint and expected
            else max(span, 0.05) if expected
            else span)
        while True:
            with self._lock:
                undrained = self._undrained
                last_t = self._last_enqueue_t
            now = clock.monotonic()
            # A pending drain (core/preempt.py) must not wait out the
            # burst gate: drain whatever is queued NOW so in-flight
            # collectives finish before the drain commit's grace
            # window burns down.
            if expected > 0:
                if (undrained == 0 or undrained >= expected
                        or now >= deadline or self._stop.is_set()
                        or preempt.pending()):
                    break
            elif (undrained == 0 or now - last_t >= quiesce
                    or now >= deadline or self._stop.is_set()
                    or preempt.pending()):
                break
            clock.sleep(min(quiesce / 2, max(deadline - now, 1e-4)))

    def _note_drained(self, drained: int, req: bytes
                      ) -> wire.RequestList:
        """Burst-stability bookkeeping + bypass/resync/cache-hit
        telemetry for one drained request blob; returns the parsed
        blob for the prediction fast path."""
        if drained == self._expected_burst:
            self._burst_stable = min(self._burst_stable + 1, 8)
        else:
            self._expected_burst = drained
            self._burst_stable = 0
        with self._lock:
            if self._burst_hint and drained >= self._burst_hint:
                self._burst_hint = 0  # consumed; hooks re-arm per step
        parsed = wire.parse_request_list(req)
        if parsed.cache_bypass:
            _M_BYPASS.inc()
            _M_CACHE_HITS.inc(sum(
                bin(w).count("1") for w in parsed.cache_bits))
        else:
            if parsed.cache_resync:
                _M_RESYNC.inc()
                if flight.ACTIVE:
                    flight.note("resync", drained=drained)
            _M_CACHE_HITS.inc(len(parsed.cache_hits))
        return parsed

    def _dispatch_execution(self, rl: wire.ResponseList,
                            finished: List[int]):
        """Run (or hand to the pipelined executor) one applied
        ResponseList, then fold in tuning/shutdown signals."""
        if (rl.cache_resync_needed or rl.join_last_rank >= 0
                or any(rs.error for rs in rl.responses)):
            # Membership changes, coordinator-forced resyncs and error
            # responses (mismatch diagnostics included) invalidate
            # everything the predictor learned — including the burst
            # gate's _expected_burst itself: a stale steady size from
            # before a resize/mismatch would gate (and predict) the
            # wrong burst shape until it happened to repeat.
            with self._lock:
                self._reset_predict_state()
        if tracing.ACTIVE and rl.responses:
            # Negotiation is over for these tensors: they now wait for
            # executor pickup.  op_phase no-ops for names this rank
            # does not hold live (responses broadcast to all ranks,
            # including non-members of the response's process set).
            for rs in rl.responses:
                if not rs.error:
                    for n in rs.tensor_names:
                        tracing.op_phase(n, tracing.QUEUE)
        if rl.responses or rl.join_last_rank >= 0:
            if self._exec_queue is not None:
                # pipelined: the executor thread runs the data plane
                # while this thread proceeds to the next drain /
                # exchange.  Bounded queue: if the executor falls
                # behind, negotiation throttles instead of ballooning.
                while True:
                    try:
                        self._exec_queue.put((rl, finished), timeout=0.5)
                        break
                    except queue.Full:
                        if self._stop.is_set():
                            break
            else:
                self._execute(rl, finished)
        if rl.responses and self._autotuner is not None and self.rank == 0:
            # Parity: ParameterManager.Update — the COORDINATOR scores
            # each cycle by the bytes it moved and publishes the
            # tuner's current (fusion threshold, cycle time) in the
            # next ResponseList, so every rank applies identical values
            # (per-rank tuners would diverge: scores depend on local
            # wall clock).
            self._autotuner.record_step(
                sum(rs.total_bytes for rs in rl.responses)
            )
            thr, cyc_ms = self._autotuner.current
            self._ctrl.set_tuned(int(thr), int(cyc_ms * 1000.0))
        if rl.tuned_fusion_threshold >= 0:
            self._ctrl.set_fusion_threshold(int(rl.tuned_fusion_threshold))
            self._tuned_seen = True  # tuning in play: prediction off
        if rl.tuned_cycle_time_us >= 0:
            self.cycle_time_s = rl.tuned_cycle_time_us / 1e6
            self._tuned_seen = True
        if rl.shutdown:
            self._shutdown_seen.set()
        with self._lock:
            _M_QUEUE_DEPTH.set(len(self._payloads))
        cache_size = getattr(self._ctrl, "cache_size", None)
        if callable(cache_size):
            try:
                _M_CACHE_SIZE.set(cache_size())
            except Exception:
                pass

    def run_cycle_once(self) -> bool:
        """One lockstep coordination cycle (parity: RunLoopOnce) —
        the single-process / manual-test path; multi-process KV worlds
        use the streamed loops below instead.  Returns True when the
        cycle carried work (requests drained or responses executed) —
        the loop's idle-backoff signal."""
        t_cycle0 = clock.monotonic()
        self._gate_burst()
        cycle = self._cycle
        self._cycle += 1
        if self._timeline is not None and getattr(
                self._timeline, "mark_cycles", False):
            self._timeline.mark_cycle(cycle)
        with self._lock:
            # counter reset and drain in ONE critical section: an
            # enqueue between them would be drained yet still counted,
            # making the next cycle's gate wait on a phantom op
            drained = self._undrained
            self._undrained = 0
            req = self._ctrl.drain_requests()
        if drained:
            self._note_drained(drained, req)
        resp_blob = self._transport.exchange(self._ctrl, cycle, req)
        self._drain_arrival_skew()
        finished = self._ctrl.apply_responses(resp_blob)
        rl = wire.parse_response_list(resp_blob)
        active = bool(rl.responses) or drained > 0
        self._dispatch_execution(rl, finished)
        if cycle % 256 == 0:
            self._inspect_stalls()
        _M_CYCLES.inc()
        _M_CYCLE_S.observe(clock.monotonic() - t_cycle0)
        return active

    def _inspect_stalls(self):
        # Parity: stall_inspector.cc — name the tensors and the missing
        # ranks; warn once per tensor, abort past the shutdown deadline.
        # Rank 0 (coordinator) sees per-rank presence via its message
        # table; every OTHER rank watchdogs its own pending payloads by
        # age so a stalled collective surfaces everywhere, not just on
        # the coordinator.
        if self.rank != 0:
            self._inspect_local_stalls()
            return
        for s in self._ctrl.check_stalls():
            key = s["name"]
            if key not in self._stall_logged:
                self._stall_logged.add(key)
                obs_metrics.counter("hvtpu_stall_warnings_total").inc()
                logger.warning(
                    "stalled collective %r: waited %.1fs; ranks ready %s, "
                    "ranks missing %s",
                    s["name"], s["waiting_s"], s["present"], s["missing"],
                )
                if tracing.ACTIVE:
                    tracing.instant(
                        "stall_warning", tensor=s["name"],
                        waited_s=s["waiting_s"],
                        ranks_present=s["present"],
                        ranks_missing=s["missing"])
            if (self.stall_abort_s > 0
                    and s["waiting_s"] > self.stall_abort_s):
                obs_metrics.counter("hvtpu_stall_aborts_total").inc()
                if flight.ACTIVE:
                    flight.note("stall_abort", tensor=s["name"],
                                waited_s=round(s["waiting_s"], 3),
                                ranks_missing=s["missing"])
                flight.dump_postmortem("stall_abort", tensor=s["name"])
                raise HorovodInternalError(
                    f"collective {s['name']!r} stalled for "
                    f"{s['waiting_s']:.0f}s; missing ranks {s['missing']}"
                )

    def _inspect_local_stalls(self):
        """Age-based watchdog for non-coordinator ranks: they cannot see
        which ranks are missing (only rank 0's message table can), but
        they can tell their own op has waited too long."""
        now = clock.monotonic()
        with self._lock:
            pending = [(p.name, now - p.t_enqueue)
                       for p in self._payloads.values()]
        for name, waited in pending:
            if waited < self.stall_warn_s:
                continue
            key = f"local:{name}"
            if key not in self._stall_logged:
                self._stall_logged.add(key)
                obs_metrics.counter("hvtpu_stall_warnings_total").inc()
                logger.warning(
                    "stalled collective %r: waited %.1fs on rank %d "
                    "(coordinator rank 0 logs which ranks are missing)",
                    name, waited, self.rank,
                )
                if tracing.ACTIVE:
                    tracing.instant(
                        "stall_warning", tensor=name,
                        waited_s=waited, rank=self.rank)
            if self.stall_abort_s > 0 and waited > self.stall_abort_s:
                obs_metrics.counter("hvtpu_stall_aborts_total").inc()
                if flight.ACTIVE:
                    flight.note("stall_abort", tensor=name,
                                waited_s=round(waited, 3),
                                rank=self.rank)
                flight.dump_postmortem("stall_abort", tensor=name)
                raise HorovodInternalError(
                    f"collective {name!r} stalled for {waited:.0f}s on "
                    f"rank {self.rank}"
                )

    # ---- live introspection (/debug) ----
    def debug_state(self) -> dict:
        """JSON-serializable snapshot of live controller state, served
        by the metrics HTTP server's /debug endpoint (registered at
        start(), removed at stop())."""
        with self._lock:
            queue_depth = len(self._payloads)
            undrained = self._undrained
            unscheduled = len(self._unsched)
            predicted_in_flight = len(self._predicted)
            in_flight = sorted(self._by_name)[:64]
        out: Dict[str, Any] = {
            "rank": self.rank,
            "size": self.size,
            "plane": "streamed" if self._stream else "lockstep",
            "cycle": self._cycle,
            "stream_req_idx": self._req_idx,
            "stream_next_resp": self._next_resp,
            "queue_depth": queue_depth,
            "undrained": undrained,
            "unscheduled": unscheduled,
            "predicted_in_flight": predicted_in_flight,
            "in_flight_ops": in_flight,
            "thread_error": (repr(self._thread_error)
                             if self._thread_error else None),
            "cache": {"capacity": self._cache_capacity},
        }
        # cache_size is a property on the Python twin, a method on the
        # native controller; tolerate both (and a closing controller).
        cs = getattr(self._ctrl, "cache_size", None)
        try:
            out["cache"]["size"] = int(cs() if callable(cs) else cs)
        except Exception:
            pass
        for attr in ("pending_count", "pending_bytes"):
            v = getattr(self._ctrl, attr, None)
            if v is not None and not callable(v):
                out[attr] = int(v)
        if self.rank == 0:
            ps = getattr(self._ctrl, "pending_summary", None)
            if callable(ps):
                out["pending_coordination"] = ps()
        return out

    # ---- execution (parity: PerformOperation dispatching to ops/*) ----
    def _zero_payload(self, rs: wire.Response, i: int) -> _Payload:
        """Synthetic zero-contribution payload for a tensor this (joined)
        rank never enqueued (parity: JoinOp substituting a zero tensor
        so the data-plane collective still has all mesh members).

        The response's dtype is the WIRE dtype, so zeros built from it
        line up element-for-element with peers' compressed buffers.
        Allgather/alltoall contribute zero rows instead.
        """
        name = rs.tensor_names[i]
        shape = tuple(rs.tensor_shapes[i]) if i < len(rs.tensor_shapes) else ()
        dtype = jnp.dtype(wire.DTYPE_NAMES.get(rs.dtype, "float32"))
        kind_map = {
            wire.ALLREDUCE: "allreduce", wire.ALLGATHER: "allgather",
            wire.BROADCAST: "broadcast", wire.ALLTOALL: "alltoall",
            wire.REDUCESCATTER: "reducescatter", wire.BARRIER: "barrier",
        }
        kind = kind_map.get(rs.type, "allreduce")
        splits = None
        if kind in ("allgather", "alltoall"):
            shape = (0,) + shape[1:]
        if kind == "alltoall":
            members = self._ps_ranks.get(rs.process_set_id)
            p = len(members) if members else self.size
            splits = [0] * p
        fut = OpFuture(name)
        fut.set_result(None)  # nobody waits on a joined rank's result
        return _Payload(
            seq=-1, name=name, future=fut,
            tensor=jnp.zeros(shape, dtype),
            rop=_WIRE_TO_RED.get(rs.red_op, ReduceOp.SUM),
            prescale=1.0, postscale=1.0, compressor=NoneCompressor,
            splits=splits, kind=kind, process_set=rs.process_set_id,
            psid=rs.process_set_id, root_rank=rs.root_rank,
            t_enqueue=clock.monotonic(),
        )

    def _take_payloads(self, rs: wire.Response,
                       strict: bool = True) -> List[_Payload]:
        """Pop this rank's payloads for a response (name + matching
        process-set id, both dicts updated together under the lock).

        ``strict=True`` (normal execution): a missing payload means a
        joined rank zero-substitutes, anything else is protocol
        corruption.  ``strict=False`` (error responses): missing
        payloads are skipped — members that never enqueued the tensor
        legitimately receive the broadcast error.
        """
        out = []
        with self._lock:
            for i, n in enumerate(rs.tensor_names):
                seq = self._by_name.get(n)
                if (seq is not None
                        and self._payloads[seq].psid == rs.process_set_id):
                    del self._by_name[n]
                    out.append(self._payloads.pop(seq))
                elif not strict:
                    continue
                elif n in self._mispredict_names:
                    # already executed (and resolved) from a predicted
                    # schedule whose confirmation was later abandoned;
                    # the late real response is pure bookkeeping
                    self._mispredict_names.discard(n)
                    continue
                elif self._joined_local:
                    out.append(self._zero_payload(rs, i))
                else:
                    raise HorovodInternalError(
                        f"response names unknown tensor {n!r} "
                        f"(process set {rs.process_set_id})"
                    )
        return out

    def _member_of(self, psid: int) -> bool:
        ranks = self._ps_ranks.get(psid)
        return ranks is None or self.rank in ranks

    def _fail_error_response(self, rs: wire.Response):
        """Fail futures for an ERROR response.  Only payloads this rank
        actually has are failed — error responses (e.g. 'rank N has
        shut down') legitimately reach member ranks that never enqueued
        the tensor, which must not be treated as protocol corruption.

        Cross-rank mismatch errors (the coordinator's named-rank
        diagnostics) surface as the typed
        :class:`~horovod_tpu.core.exceptions.HvtpuMismatchError` so
        callers can distinguish "my ranks disagree" (a program bug to
        fix) from transient internal failures an elastic loop should
        absorb-and-restart."""
        err_cls = HorovodInternalError
        if rs.error.startswith(_MISMATCH_MARKER):
            err_cls = HvtpuMismatchError
            _M_MISMATCH.inc()
            logger.error("coordinator mismatch diagnostics: %s", rs.error)
        for p in self._take_payloads(rs, strict=False):
            if self._timeline is not None:
                self._timeline.end(p.name)
            if tracing.ACTIVE:
                tracing.op_done(p.name, error=rs.error)
            p.future.set_error(err_cls(rs.error))

    def _execute(self, rl: wire.ResponseList, finished: List[int]):
        with eager_comm.controller_execution():
            self._execute_responses(rl, finished)

    def _execute_responses(self, rl: wire.ResponseList,
                            finished: List[int]):
        for rs in rl.responses:
            # Responses are broadcast to every rank; only member ranks
            # of the response's process set execute it (parity: each
            # set's communicator spans exactly its members).
            if not self._member_of(rs.process_set_id):
                continue
            if rs.error:
                self._fail_error_response(rs)
                continue
            payloads = self._take_payloads(rs)
            now = clock.monotonic()
            # Batched bookkeeping: ONE histogram lock acquisition for
            # the whole fused group instead of a per-op round trip.
            waits = [now - p.t_enqueue for p in payloads if p.seq != -1]
            if waits:
                _M_NEGOTIATION_S.observe_many(waits)
            if self._timeline is not None:
                for p in payloads:
                    if p.seq != -1:  # not a synthetic zero payload
                        self._timeline.end(p.name)
            try:
                self._execute_one(rs, payloads)
            except Exception as e:
                # Data-plane failure: fail exactly this response's futures
                # (parity: entry.callback(Status error) in
                # PerformOperation's error path).
                for p in payloads:
                    if not p.future.done():
                        if tracing.ACTIVE:
                            tracing.op_done(p.name, error=str(e))
                        p.future.set_error(HorovodInternalError(str(e)))
        if rl.join_last_rank >= 0:
            with self._lock:
                futs, self._join_futures = self._join_futures, []
                self._joined_local = False
            for f in futs:
                f.set_result(rl.join_last_rank)

    def _execute_one(self, rs: wire.Response, payloads: List[_Payload]):
        # Each op executes over ITS process set (parity: PerformOperation
        # looking up the Response's process_set_id communicator); the
        # controller negotiated readiness among exactly those ranks.
        if rs.type == wire.BARRIER:
            for p in payloads:
                if tracing.ACTIVE:
                    tracing.op_phase(p.name, tracing.EXEC)
                eager_comm.barrier(process_set=p.process_set)
                p.future.set_result(None)
                if tracing.ACTIVE:
                    tracing.op_done(p.name)
            return
        if rs.type == wire.ALLREDUCE:
            self._execute_allreduce(rs, payloads)
            return
        if tracing.ACTIVE:
            for p in payloads:
                tracing.op_phase(p.name, tracing.EXEC)
        if rs.type == wire.ALLGATHER:
            for p in payloads:
                p.future.set_result(
                    eager_comm.allgather(p.tensor,
                                         process_set=p.process_set)
                )
        elif rs.type == wire.BROADCAST:
            for p in payloads:
                p.future.set_result(
                    eager_comm.broadcast(p.tensor, root_rank=rs.root_rank,
                                         process_set=p.process_set)
                )
        elif rs.type == wire.ALLTOALL:
            for p in payloads:
                p.future.set_result(
                    eager_comm.alltoall(p.tensor, p.splits,
                                        process_set=p.process_set)
                )
        elif rs.type == wire.REDUCESCATTER:
            for p in payloads:
                p.future.set_result(
                    eager_comm.reducescatter(p.tensor, op=p.rop,
                                             process_set=p.process_set)
                )
        else:  # pragma: no cover
            raise HorovodInternalError(f"unknown response type {rs.type}")
        if tracing.ACTIVE:
            for p in payloads:
                tracing.op_done(p.name, bytes=rs.total_bytes)

    def _execute_allreduce(self, rs: wire.Response, payloads: List[_Payload]):
        from ..comm.spmd import _is_int8

        rop = _WIRE_TO_RED[rs.red_op]
        unfusable = (
            rs.red_op == wire.RED_ADASUM
            # int8's per-chunk scales don't sum across ranks outside the
            # quantized-allreduce kernel; keep it on the per-tensor path
            # (subclass-aware: Int8StochasticCompressor must not slip
            # onto the fused path either).
            or any(_is_int8(p.compressor) for p in payloads)
        )
        if unfusable or len(payloads) == 1:
            # Adasum stays per-tensor (scale-invariance is per-tensor);
            # single-tensor responses skip the pack entirely.
            for p in payloads:
                if tracing.ACTIVE:
                    tracing.op_phase(p.name, tracing.EXEC)
                out = eager_comm.allreduce(
                    p.tensor, op=p.rop,
                    prescale_factor=p.prescale,
                    postscale_factor=p.postscale,
                    compression=p.compressor,
                    name=p.name,
                    process_set=p.process_set,
                )
                p.future.set_result(out)
                if tracing.ACTIVE:
                    # Wire bytes — what the collective actually moved
                    # (post-compression), not the host tensor's size.
                    wdt = jnp.dtype(
                        p.compressor.wire_dtype(p.tensor.dtype))
                    tracing.op_done(
                        p.name,
                        bytes=int(p.tensor.size * wdt.itemsize))
            return
        # Fused execution.  Zero-copy fast path first: when EVERY
        # payload of this group was packed at enqueue time into one
        # complete exchange buffer (the learned plan matched the agreed
        # grouping), the wire tensor is a typed view of that buffer —
        # no drain-time concatenate, and futures resolve with lazy
        # unpack views.  Anything less falls back to the staged copy
        # path below, counted by the metric pair so profiles prove
        # which path ran.
        gkey = (payloads[0].psid, tuple(rs.tensor_names))
        with self._lock:
            pack = self._open_packs.pop(gkey, None)
        zero_copy = (
            pack is not None
            and pack.complete()
            and len(payloads) == len(pack.specs)
            and all(getattr(p, "prepacked", None) == gkey
                    for p in payloads)
        )
        if pack is not None and not zero_copy:
            # Stale/partial pack (mispredicted grouping, a payload that
            # failed its slot checks): return it and stage.
            self._fusion_pool.release(payloads[0].psid, pack)
            pack = None
        if zero_copy:
            self._execute_allreduce_zero_copy(rs, payloads, pack, rop)
            return
        # Staged path: per-tensor prescale & wire-compression commute
        # with elementwise reduction, so apply them per tensor around
        # ONE flat collective (parity: MemcpyInFusionBuffer -> single
        # ncclAllReduce -> MemcpyOutFusionBuffer).
        if tracing.ACTIVE:
            tracing.op_phase_many([p.name for p in payloads],
                                  tracing.FUSE)
        wires, ctxs = [], []
        for p in payloads:
            t = p.tensor
            if p.prescale != 1.0:
                t = _apply_scale(t, p.prescale)
            t, ctx = p.compressor.compress(t)
            wires.append(t)
            ctxs.append(ctx)
        flat, specs = pack_flat(wires)
        if tracing.ACTIVE:
            tracing.op_phase_many([p.name for p in payloads],
                                  tracing.EXEC)
        # The fuser only merges responses with equal process_set_id
        # (fallback._fuse / Controller::FuseResponses), so the group's
        # shared set is payloads[0]'s.
        red = eager_comm.allreduce(
            flat, op=rop, name=f"fused.{rs.tensor_names[0]}.{len(payloads)}",
            process_set=payloads[0].process_set,
        )
        _M_FUSION_STAGED.inc(len(payloads))
        done_items = []
        for p, ctx, spec, piece in zip(payloads, ctxs, specs,
                                       unpack_flat(red, specs)):
            out = p.compressor.decompress(piece, ctx)
            if p.postscale != 1.0:
                out = _apply_scale(out, p.postscale)
            p.future.set_result(out)
            # Wire bytes: this op's share of the flat buffer in the
            # promoted wire dtype (post-compression).
            done_items.append(
                (p.name,
                 {"bytes": int(spec[2] * flat.dtype.itemsize)}))
        if tracing.ACTIVE:
            tracing.op_done_many(done_items, fused=len(payloads),
                                 zero_copy=False)
        self._maybe_learn_pack_plan(rs, payloads)

    def _execute_allreduce_zero_copy(self, rs, payloads, pack, rop):
        """Fused allreduce over an enqueue-time-packed exchange buffer:
        the wire tensor is a typed view of the pooled host buffer (no
        concatenate), futures resolve with :class:`_LazyPiece` views
        (unpack deferred into the consumer), and all per-op
        bookkeeping is batched — one tracing flush, one metrics
        update."""
        names = [p.name for p in payloads]
        if tracing.ACTIVE:
            tracing.op_phase_many(names, tracing.EXEC)
        flat = jnp.asarray(pack.typed_view())
        red = eager_comm.allreduce(
            flat, op=rop,
            name=f"fused.{rs.tensor_names[0]}.{len(payloads)}",
            process_set=payloads[0].process_set,
        )
        group = _GroupUnpack(red, pack.element_specs(), pack,
                             self._fusion_pool, payloads[0].psid)
        done_items = []
        for i, p in enumerate(payloads):
            p.future.set_result(_LazyPiece(group, i, p.postscale))
            done_items.append(
                (p.name, {"bytes": int(pack.specs[i][2])}))
        _M_FUSION_ZC.inc(len(payloads))
        if tracing.ACTIVE:
            tracing.op_done_many(done_items, fused=len(payloads),
                                 zero_copy=True)
