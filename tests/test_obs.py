"""Timeline + autotuner tests (parity targets: timeline.cc Chrome-trace
output, ParameterManager sampling/pinning)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.core.config import Config
from horovod_tpu.obs.autotune import Autotuner
from horovod_tpu.obs.timeline import ICI_ALLREDUCE, QUEUE, Timeline


class TestTimeline:
    def test_chrome_trace_roundtrip(self, tmp_path):
        path = str(tmp_path / "tl.json")
        tl = Timeline(path, rank=0)
        tl.begin("grad/w1", QUEUE)
        tl.end("grad/w1")
        tl.begin("grad/w1", ICI_ALLREDUCE)
        tl.end("grad/w1")
        tl.instant("cycle_start", index=0)
        tl.mark_cycle(1)
        tl.close()
        events = json.load(open(path))
        names = [e["name"] for e in events]
        assert QUEUE in names and ICI_ALLREDUCE in names
        # B/E pairs balance
        assert names.count(QUEUE) == 2 or (
            sum(1 for e in events if e.get("ph") == "B")
            == sum(1 for e in events if e.get("ph") == "E")
        )

    def test_close_idempotent_and_end_without_begin(self, tmp_path):
        path = str(tmp_path / "tl2.json")
        tl = Timeline(path, rank=1)
        tl.end("never-started")  # no-op, no crash
        tl.close()
        tl.close()
        json.load(open(path))

    def test_api_start_stop(self, hvt, tmp_path):
        path = str(tmp_path / "tl3.json")
        tl = hvt.start_timeline(path)
        tl.begin("t", QUEUE)
        tl.end("t")
        hvt.stop_timeline()
        assert json.load(open(path))


class TestAutotuner:
    def _mk(self, steps_per_sample=2, warmup=0):
        cfg = Config(
            autotune=True,
            autotune_steps_per_sample=steps_per_sample,
            autotune_warmup_samples=warmup,
        )
        return Autotuner(cfg)

    def test_sweeps_then_pins(self):
        tuner = self._mk()
        seen = set()
        for _ in range(100):
            seen.add(tuner.current)
            tuner.record_step(1 << 20)
            if tuner.done:
                break
        assert tuner.done
        assert len(seen) > 1  # actually explored
        assert tuner.current in seen

    def test_warmup_skipped(self):
        tuner = self._mk(warmup=3)
        first = tuner.current
        for _ in range(3):
            tuner.record_step(1)
        assert tuner.current == first  # still on first candidate

    def test_log_csv(self, tmp_path):
        cfg = Config(
            autotune=True, autotune_steps_per_sample=1,
            autotune_warmup_samples=0,
            autotune_log=str(tmp_path / "at.csv"),
        )
        tuner = Autotuner(cfg)
        while not tuner.done:
            tuner.record_step(1 << 20)
        lines = open(cfg.autotune_log).read().strip().splitlines()
        assert lines[0].startswith("fusion_threshold")
        assert len(lines) > 1


AXIS = "world"


class TestQuantizedAllreduce:
    def _mesh(self):
        return Mesh(np.asarray(jax.devices(), dtype=object), (AXIS,))

    def test_int8_wire_matches_fp32_within_tolerance(self):
        from horovod_tpu.comm import Compression, ReduceOp, spmd

        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(8, 1000).astype(np.float32))

        def body(s):
            return spmd.allreduce(
                s[0], axis_name=AXIS, op=ReduceOp.SUM,
                compression=Compression.int8,
            )[None]

        out = jax.jit(
            jax.shard_map(
                body, mesh=self._mesh(), in_specs=(P(AXIS),),
                out_specs=P(AXIS), check_vma=False,
            )
        )(x)
        exact = np.asarray(x).sum(0)
        got = np.asarray(out[0])
        # two quantization stages ⇒ bounded relative error
        scale = np.abs(np.asarray(x)).max()
        assert np.abs(got - exact).max() < 0.05 * scale * 8

    def test_average_and_shape_restore(self):
        from horovod_tpu.comm import Compression, ReduceOp, spmd

        x = jnp.ones((8, 3, 7), jnp.float32) * 2.0

        def body(s):
            return spmd.allreduce(
                s[0], axis_name=AXIS, op=ReduceOp.AVERAGE,
                compression=Compression.int8,
            )[None]

        out = jax.jit(
            jax.shard_map(
                body, mesh=self._mesh(), in_specs=(P(AXIS),),
                out_specs=P(AXIS), check_vma=False,
            )
        )(x)
        assert out.shape == (8, 3, 7)
        np.testing.assert_allclose(np.asarray(out[0]), np.full((3, 7), 2.0),
                                   rtol=2e-2)

    def test_int8_with_groups_rejected(self):
        from horovod_tpu.comm import Compression, ReduceOp, spmd

        x = jnp.ones((8, 4))
        with pytest.raises(NotImplementedError):
            def body(s):
                return spmd.allreduce(
                    s[0], axis_name=AXIS, op=ReduceOp.SUM,
                    compression=Compression.int8,
                    groups=[[0, 1, 2, 3], [4, 5, 6, 7]],
                )[None]

            jax.jit(
                jax.shard_map(
                    body, mesh=self._mesh(), in_specs=(P(AXIS),),
                    out_specs=P(AXIS), check_vma=False,
                )
            )(x)


class TestGroupedEdgeCases:
    def test_empty_list(self, hvt):
        assert hvt.grouped_allreduce([]) == []

    def test_min_op_keeps_per_tensor_semantics(self, hvt):
        outs = hvt.grouped_allreduce(
            [jnp.asarray([1.0, 5.0]), jnp.asarray([2.0])], op=hvt.Min
        )
        np.testing.assert_allclose(np.asarray(outs[0]), [1.0, 5.0])


class TestReviewRegressions:
    """Regression tests for code-review findings on the initial build."""

    def test_int8_instance_also_routes_to_quantized(self):
        from horovod_tpu.comm import ReduceOp, spmd
        from horovod_tpu.comm.compression import Int8Compressor

        x = jnp.ones((8, 64), jnp.float32) * 3.0

        def body(s):
            return spmd.allreduce(
                s[0], axis_name=AXIS, op=ReduceOp.AVERAGE,
                compression=Int8Compressor(),  # instance, not class
            )[None]

        out = jax.jit(
            jax.shard_map(
                body,
                mesh=Mesh(np.asarray(jax.devices(), dtype=object), (AXIS,)),
                in_specs=(P(AXIS),), out_specs=P(AXIS), check_vma=False,
            )
        )(x)
        np.testing.assert_allclose(np.asarray(out[0]), np.full((64,), 3.0),
                                   rtol=2e-2)

    def test_unequal_groups_rejected_for_gather_ops(self):
        from horovod_tpu.comm import spmd

        with pytest.raises(ValueError, match="equal-size"):
            spmd._require_equal_groups([[0, 1, 2], [3]], "allgather")
        # equal groups pass
        spmd._require_equal_groups([[0, 1], [2, 3]], "allgather")

    def test_device_groups_equal_chunks(self, hvt):
        # single-process world: global set → None (nothing to chunk)
        table = hvt.core.global_state().process_set_table
        assert table.global_process_set.device_groups() is None

    def test_mark_cycles_gated(self, tmp_path):
        import json as _json

        p1 = str(tmp_path / "on.json")
        tl = Timeline(p1, 0, mark_cycles=True)
        tl.mark_cycle(0)
        tl.close()
        assert any(e["name"] == "CYCLE" for e in _json.load(open(p1)))
        p2 = str(tmp_path / "off.json")
        tl = Timeline(p2, 0, mark_cycles=False)
        tl.mark_cycle(0)
        tl.close()
        assert not any(e["name"] == "CYCLE" for e in _json.load(open(p2)))

    def test_autotuner_cleared_on_shutdown(self, monkeypatch):
        import horovod_tpu as hvt_mod

        monkeypatch.setenv("HVTPU_AUTOTUNE", "1")
        hvt_mod.init()
        assert hvt_mod.core.global_state().autotuner is not None
        hvt_mod.shutdown()
        monkeypatch.delenv("HVTPU_AUTOTUNE")
        hvt_mod.init()
        try:
            assert hvt_mod.core.global_state().autotuner is None
        finally:
            hvt_mod.shutdown()

    def test_poll_handles_tuple_results(self, hvt):
        h = hvt.alltoall_async(jnp.ones((2, 1)), splits=[2])
        assert hvt.poll(h) in (True, False)  # no crash on tuple
        out, splits = hvt.synchronize(h)
        assert out.shape == (2, 1)


class TestFusedAdasumSegments:
    """Fused Adasum must be bucketing-invariant (per-tensor dots)."""

    def _run_fused(self, tree, threshold):
        from horovod_tpu.comm import ReduceOp
        from horovod_tpu.comm.fusion import fused_tree_allreduce

        def body(t):
            return fused_tree_allreduce(
                t, axis_name=AXIS, threshold_bytes=threshold,
                op=ReduceOp.ADASUM,
            )

        return jax.jit(
            jax.shard_map(
                body,
                mesh=Mesh(np.asarray(jax.devices(), dtype=object), (AXIS,)),
                in_specs=(P(AXIS),), out_specs=P(AXIS), check_vma=False,
            )
        )(tree)

    def test_threshold_invariance(self):
        rng = np.random.RandomState(21)
        tree = {
            "big": jnp.asarray(rng.randn(8, 64).astype(np.float32) * 100.0),
            "small": jnp.asarray(rng.randn(8, 16).astype(np.float32) * 0.01),
        }
        fused = self._run_fused(tree, 1 << 30)      # one bucket
        unfused = self._run_fused(tree, 1)          # per-tensor buckets
        for k in tree:
            np.testing.assert_allclose(
                np.asarray(fused[k]), np.asarray(unfused[k]),
                rtol=1e-4, atol=1e-6,
            )

    def test_adasum_int8_rejected(self):
        from horovod_tpu.comm import Compression, ReduceOp, spmd

        with pytest.raises(ValueError, match="int8"):
            def body(s):
                return spmd.allreduce(
                    s[0], axis_name=AXIS, op=ReduceOp.ADASUM,
                    compression=Compression.int8,
                )[None]

            jax.jit(
                jax.shard_map(
                    body,
                    mesh=Mesh(np.asarray(jax.devices(), dtype=object), (AXIS,)),
                    in_specs=(P(AXIS),), out_specs=P(AXIS), check_vma=False,
                )
            )(jnp.ones((8, 4)))


class TestAutotunerWiring:
    def test_eager_path_consumes_autotuner(self, monkeypatch):
        import optax

        import horovod_tpu as hvt_mod
        from horovod_tpu.api.optimizer import allreduce_gradients

        monkeypatch.setenv("HVTPU_AUTOTUNE", "1")
        monkeypatch.setenv("HVTPU_AUTOTUNE_WARMUP_SAMPLES", "0")
        monkeypatch.setenv("HVTPU_AUTOTUNE_STEPS_PER_SAMPLE", "1")
        hvt_mod.init()
        try:
            tuner = hvt_mod.core.global_state().autotuner
            assert tuner is not None
            grads = {"w": jnp.ones((8, 8))}
            first = tuner.current
            while not tuner.done:
                allreduce_gradients(grads, axis_name=None)
            # the sweep ran: candidates consumed via the eager path
            assert tuner.done
        finally:
            hvt_mod.shutdown()

    def test_timeline_records_eager_allreduce(self, monkeypatch, tmp_path):
        import json as _json

        import horovod_tpu as hvt_mod

        hvt_mod.init()
        try:
            hvt_mod.start_timeline(str(tmp_path / "t.json"))
            hvt_mod.allreduce(jnp.ones((4,)), name="grad/w")
            hvt_mod.stop_timeline()
            events = _json.load(open(tmp_path / "t.json"))
            assert any(
                e.get("args", {}).get("tensor") == "grad/w" for e in events
            )
        finally:
            hvt_mod.shutdown()


class TestAutotunerControllerWiring:
    """VERDICT round-1 task 6(b): the autotuner's cycle-time AND fusion
    threshold must reach the LIVE controller, not just the jit path."""

    def test_autotuner_applies_to_controller(self, hvt):
        from horovod_tpu.core.config import Config
        from horovod_tpu.eager.controller import EagerController
        from horovod_tpu.obs.autotune import Autotuner

        grid = [(1 << 20, 2.0), (4 << 20, 7.5)]
        cfg = Config(autotune=True, autotune_warmup_samples=0,
                     autotune_steps_per_sample=1)
        tuner = Autotuner(cfg, grid=grid)
        ctrl = EagerController(0, 1, manual=True, autotuner=tuner,
                               fusion_threshold=64 << 20,
                               cycle_time_ms=1.0)
        try:
            import jax.numpy as jnp

            # candidate 0 scores and candidate 1 is PUBLISHED in the
            # next ResponseList (ParameterManager-broadcast parity);
            # one more cycle applies it on every rank
            ctrl.enqueue("allreduce", jnp.ones(8), name="t0")
            ctrl.run_cycle_once()
            ctrl.run_cycle_once()  # empty cycle carries tuned params
            assert ctrl.cycle_time_s == grid[1][1] / 1000.0
            assert ctrl._ctrl.fusion_threshold == grid[1][0]
            # second scored step pins the best and keeps applying it
            ctrl.enqueue("allreduce", jnp.ones(8), name="t1")
            ctrl.run_cycle_once()
            ctrl.run_cycle_once()
            assert tuner.done
            assert (ctrl._ctrl.fusion_threshold, ctrl.cycle_time_s * 1000.0) \
                == tuner.current
        finally:
            ctrl.stop()


class TestLogLevelWiring:
    def test_log_level_applied_at_init(self):
        import logging

        import horovod_tpu as hvt_mod
        from horovod_tpu.core.config import Config

        hvt_mod.shutdown()
        try:
            hvt_mod.init(Config(log_level="debug"))
            assert (logging.getLogger("horovod_tpu").level
                    == logging.DEBUG)
        finally:
            hvt_mod.shutdown()
            hvt_mod.init(Config(log_level="warning"))
            assert (logging.getLogger("horovod_tpu").level
                    == logging.WARNING)
            hvt_mod.shutdown()


class TestGaussianProcess:
    def test_gp_fits_and_predicts(self):
        import numpy as np

        from horovod_tpu.obs.gaussian_process import GaussianProcess

        rng = np.random.RandomState(0)
        x = rng.rand(20, 1)
        y = np.sin(6 * x[:, 0])
        gp = GaussianProcess(length_scale=0.2, noise=1e-6)
        gp.fit(x, y)
        mu, sigma = gp.predict(x)
        np.testing.assert_allclose(mu, y, atol=1e-2)
        # uncertainty grows away from data
        _, s_far = gp.predict(np.asarray([[5.0]]))
        assert s_far[0] > sigma.mean() * 3

    def test_ei_prefers_promising_region(self):
        import numpy as np

        from horovod_tpu.obs.gaussian_process import (
            GaussianProcess,
            expected_improvement,
        )

        x = np.asarray([[0.0], [0.5], [1.0]])
        y = np.asarray([0.0, 1.0, 0.1])
        gp = GaussianProcess(length_scale=0.2, noise=1e-6)
        gp.fit(x, y)
        cand = np.linspace(0, 1, 101)[:, None]
        ei = expected_improvement(gp, cand, best_y=1.0)
        # best EI near the known max, not at the poor edges
        assert 0.25 <= float(cand[np.argmax(ei)][0]) <= 0.75

    def test_bayesian_optimizer_finds_peak(self):
        import numpy as np

        from horovod_tpu.obs.gaussian_process import BayesianOptimizer

        def score(pt):  # peak at (0.3, 0.7) in unit coords
            u = (np.asarray(pt) - np.asarray([0.0, 0.0])) / 10.0
            return -((u[0] - 0.3) ** 2 + (u[1] - 0.7) ** 2)

        bo = BayesianOptimizer([(0.0, 10.0), (0.0, 10.0)],
                               seed_points=[(5.0, 5.0)])
        for _ in range(20):
            x = bo.suggest()
            bo.observe(x, score(x))
        best_x, _ = bo.best
        assert abs(best_x[0] - 3.0) < 2.5
        assert abs(best_x[1] - 7.0) < 2.5


class TestGpAutotuner:
    def test_gp_mode_pins_good_candidate(self):
        from horovod_tpu.core.config import Config
        from horovod_tpu.obs.autotune import Autotuner

        cfg = Config(autotune=True, autotune_warmup_samples=0,
                     autotune_steps_per_sample=1, autotune_gp_samples=10)
        tuner = Autotuner(cfg, mode="gp")
        # synthetic landscape: throughput peaks at large fusion
        # thresholds with ~2.5 ms cycle time
        import math

        def throughput(thr, cyc):
            t = math.log2(thr)
            return -((t - 26.5) ** 2) - ((cyc - 2.5) ** 2) * 0.3

        steps = 0
        while not tuner.done and steps < 100:
            thr, cyc = tuner.current
            # feed bytes so score == throughput deterministically:
            # monkeypatch via direct observe path instead
            tuner._bytes = 0
            tuner._steps = 0
            tuner._t_start = __import__("time").monotonic() - 1.0
            tuner._bytes = max(throughput(thr, cyc) + 100.0, 1e-3)
            tuner._steps = tuner._steps_per_sample - 1
            tuner.record_step(0)
            steps += 1
        assert tuner.done
        thr, cyc = tuner.current
        assert 2**24 <= thr <= 2**28.5
        assert 0.5 <= cyc <= 10.0

    def test_grid_mode_still_selects_best(self):
        from horovod_tpu.core.config import Config
        from horovod_tpu.obs.autotune import Autotuner

        grid = [(1 << 20, 1.0), (8 << 20, 2.0), (64 << 20, 4.0)]
        cfg = Config(autotune=True, autotune_warmup_samples=0,
                     autotune_steps_per_sample=1)
        tuner = Autotuner(cfg, grid=grid)
        assert tuner.mode == "grid"
        import time as _t

        scores = {grid[0]: 10, grid[1]: 99, grid[2]: 20}
        while not tuner.done:
            cand = tuner.current
            tuner._t_start = _t.monotonic() - 1.0
            tuner._bytes = scores[cand]
            tuner._steps = tuner._steps_per_sample - 1
            tuner.record_step(0)
        assert tuner.current == grid[1]
