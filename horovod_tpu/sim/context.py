"""Per-virtual-rank context: seam wiring for one simulated rank.

Production runs one rank per process, so core/faults.py and
core/preempt.py keep module-global registries.  The simulator runs N
ranks in one process; each module grew a thread-local override
(``faults.use`` / ``preempt.use``) exactly for this.  RankContext owns
one rank's instances of those seams plus its exit plumbing:

- a :class:`~horovod_tpu.core.faults.FaultRegistry` bound to this
  rank (clauses select on the VIRTUAL rank id, with per-rank seeded
  probability streams), whose ``kill`` action routes to
  :meth:`request_exit` instead of ``os._exit``;
- optionally a :class:`~horovod_tpu.core.preempt._DrainCoordinator`
  with the watcher thread replaced by scenario-pumped
  ``_poll_once()`` calls on virtual time, ``shared_pending=False``
  (N coordinators must not share the module-global fast path), and
  the same exit seam;
- **exit semantics**: called on the rank's own task thread,
  ``request_exit`` raises :class:`~.kernel.VirtualExit` immediately
  (the kill-fault path — ``inject()`` runs inline in the rank's
  call stack); called from a scheduler-thread timer (the drain
  grace-expiry path), it only sets :attr:`exit_code`, which the
  worker loop checks between steps — mirroring how a real signal
  interrupts a process at the next safe point.

``activate()`` is a context manager the rank's task body wraps itself
in; it installs the thread-local seams on entry and uninstalls them on
exit (including the VirtualExit unwind), keeping ``faults.ACTIVE``
truthful across thousands of rank activations.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

from ..core import faults
from ..core import preempt
from .kernel import SimKernel, VirtualExit

__all__ = ["RankContext"]


class RankContext:
    """One virtual rank's seam bundle (faults, drain, exit)."""

    def __init__(self, kernel: SimKernel, rank: int, size: int, *,
                 fault_spec: str = "", generation: int = 0,
                 drain_client=None, drain_grace_s: float = 30.0,
                 with_drain: bool = False):
        self.kernel = kernel
        self.rank = rank
        self.size = size
        self.exit_code: Optional[int] = None
        self.registry: Optional[faults.FaultRegistry] = None
        if fault_spec:
            self.registry = faults.FaultRegistry(
                faults.parse_spec(fault_spec), rank=rank,
                seed=kernel.seed, exit_fn=self.request_exit)
        self.coordinator = None
        if with_drain:
            self.coordinator = preempt._DrainCoordinator(
                rank=rank, size=size, grace_s=drain_grace_s,
                notice_file=None, generation=generation,
                client=drain_client, start_watcher=False,
                shared_pending=False, exit_fn=self.request_exit)

    # -- exit seam ------------------------------------------------------
    def request_exit(self, code: int) -> None:
        """Virtual-process exit: immediate on the rank's own thread,
        deferred to the next worker-loop check otherwise."""
        task = self.kernel.current_task()
        if task is not None and task.thread is threading.current_thread():
            raise VirtualExit(code)
        if self.exit_code is None:
            self.exit_code = code

    def check_exit(self) -> None:
        """Worker-loop safe point: honour a deferred exit request."""
        if self.exit_code is not None:
            raise VirtualExit(self.exit_code)

    # -- seam installation ---------------------------------------------
    @contextlib.contextmanager
    def activate(self) -> Iterator["RankContext"]:
        """Install this rank's thread-local seams for the duration of
        the rank's task body (the task's clock seam is installed by the
        kernel itself)."""
        if self.registry is not None:
            faults.use(self.registry)
        if self.coordinator is not None:
            preempt.use(self.coordinator)
        try:
            yield self
        finally:
            if self.coordinator is not None:
                preempt.use(None)
            if self.registry is not None:
                faults.use(None)
