"""Virtual-rank worker building blocks for the fabric simulator.

The scenarios (see :mod:`.scenarios`) compose these pieces:

- :class:`WorldView` — the rank/size/generation triple
  ``core/audit.py`` accepts as an injected world, so N virtual ranks
  can run the REAL digest-allgather in one process without touching
  ``core.state.global_state()``.

- :class:`SimElasticState` — a real
  :class:`~horovod_tpu.elastic.state.ObjectState` whose durable half
  stays in memory (the production ``save()`` writes
  ``HVTPU_ELASTIC_STATE_DIR`` from global rank 0 — meaningless for N
  in-process ranks) and whose audit routes through the injected
  client/world.  Everything that matters to the scenarios —
  ``commit()`` bookkeeping, the drain-boundary agreement, the
  ``worker.step`` fault site, audit cadence — is the REAL
  ``State.commit`` code path, untouched.

- :func:`patch_data_plane` — stubs the eager DATA plane
  (``horovod_tpu.comm.eager`` collectives) with identity functions.
  The simulator's subject is the CONTROL plane; the data plane needs
  an initialized jax.distributed world that cannot exist for 256
  in-process ranks.  Negotiation, fusion grouping, caching,
  prediction, confirm hashes — all real; only the final
  tensor-moving call is a no-op.

- :func:`elect_and_assign` — the KV-based survivor re-rendezvous the
  rolling-preemption scenario uses between generations: survivors
  register under the NEW generation, the lowest surviving old rank
  computes the dense renumbering and posts the assignment, everyone
  else blocking-gets it.  This mirrors the restart-based elastic
  resize (driver relaunch + rank reassignment) at protocol level.
"""

from __future__ import annotations

import contextlib
import json
from typing import Dict, Iterator, List, Optional

from ..elastic.state import ObjectState

__all__ = [
    "SimElasticState",
    "WorldView",
    "elect_and_assign",
    "patch_data_plane",
]


class WorldView:
    """Injected world for core/audit.py: rank/size/init_generation,
    treated as initialized."""

    __slots__ = ("rank", "size", "init_generation")

    def __init__(self, rank: int, size: int, init_generation: int = 0):
        self.rank = rank
        self.size = size
        self.init_generation = init_generation


class SimElasticState(ObjectState):
    """ObjectState with in-memory durable commits and an injected
    audit world.  ``durable_commits`` counts what production would
    have written to the elastic state dir — the exactly-once
    drain-commit assertion reads it."""

    def __init__(self, client=None, world: Optional[WorldView] = None,
                 **kwargs):
        self._sim_client = client
        self._sim_world = world
        self.durable_commits = 0
        super().__init__(**kwargs)

    def save(self):
        self.save_to_memory()
        self.durable_commits += 1

    def audit(self, label: str = "elastic.state") -> Optional[dict]:
        from ..core import audit as core_audit

        if core_audit.audit_every() <= 0:
            return None
        return core_audit.verify(
            self._capture(), label,
            client=self._sim_client, world=self._sim_world)

    def sync(self):  # pragma: no cover — scenarios resync via audit
        self.save_to_memory()
        self._synced = True


def _identity(tensor, *args, **kwargs):
    return tensor


def _none(*args, **kwargs):
    return None


@contextlib.contextmanager
def patch_data_plane() -> Iterator[None]:
    """Replace the eager data-plane collectives with identity stubs
    for the duration of a scenario (restored on exit).  Process-wide —
    safe because every sim rank wants the same stub and no production
    collective can run in a sim process anyway (jax.distributed is
    never initialized there)."""
    from ..comm import eager as eager_comm

    names = ("allreduce", "grouped_allreduce", "allgather", "broadcast",
             "alltoall", "reducescatter", "barrier")
    saved = {n: getattr(eager_comm, n) for n in names}
    saved_inval = eager_comm.invalidate_routing_plans
    for n in names:
        setattr(eager_comm, n, _none if n == "barrier" else _identity)
    eager_comm.invalidate_routing_plans = lambda: 0
    try:
        yield
    finally:
        for n, fn in saved.items():
            setattr(eager_comm, n, fn)
        eager_comm.invalidate_routing_plans = saved_inval


def elect_and_assign(kv, old_rank: int, survivors: List[int],
                     generation: int, timeout_ms: int = 600000
                     ) -> Dict[int, int]:
    """Survivor re-rendezvous after a membership change: every
    survivor posts its OLD rank under the new generation; the lowest
    surviving old rank computes the dense renumbering (sorted old
    ranks → 0..P'-1) and posts the assignment; everyone blocking-gets
    it.  Returns the full old-rank → new-rank map.

    ``survivors`` is each rank's *local expectation* of the surviving
    set (in production the driver recomputes it from discovery; the
    scenarios derive it from the departure events they injected), so
    the leader knows how many registrations to await.
    """
    ns = f"hvtsim_elect/{generation}"
    kv.key_value_set(f"{ns}/reg/{old_rank}", str(old_rank))
    leader = min(survivors)
    if old_rank == leader:
        got: Dict[int, int] = {}
        for r in survivors:
            got[int(kv.blocking_key_value_get(
                f"{ns}/reg/{r}", timeout_ms))] = 0
        assignment = {old: new for new, old in enumerate(sorted(got))}
        kv.key_value_set(
            f"{ns}/assign",
            json.dumps({str(k): v for k, v in assignment.items()}))
        return assignment
    raw = json.loads(kv.blocking_key_value_get(f"{ns}/assign",
                                               timeout_ms))
    return {int(k): v for k, v in raw.items()}
