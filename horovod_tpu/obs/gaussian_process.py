"""Gaussian-process regression + Expected Improvement.

Parity surface: ``horovod/common/optim/gaussian_process.cc``
(``GaussianProcessRegressor`` — RBF kernel, Cholesky solve, EI) and
``bayesian_optimization.cc`` (``BayesianOptimization::NextSample``).

Like the reference, the math lives in native code
(``native/src/gaussian_process.cc``, used when the library is
available); this numpy implementation is the executable-spec twin and
the fallback — ``tests/test_native.py`` cross-checks the two to
~1e-10, the same pattern as the controller/fallback pair.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np


class GaussianProcess:
    """GP regressor with an RBF kernel (parity: gaussian_process.cc
    alpha=noise, length_scale fixed — the reference also skips
    hyperparameter optimization)."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-6,
                 signal_variance: float = 1.0):
        self.length_scale = length_scale
        self.noise = noise
        self.signal_variance = signal_variance
        self._x: Optional[np.ndarray] = None
        self._raw_y: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_variance * np.exp(
            -0.5 * d2 / (self.length_scale ** 2)
        )

    def fit(self, x: np.ndarray, y: np.ndarray):
        """Record the data and standardisation; the O(n^3) Cholesky is
        deferred — the native path refactors from raw (x, y) itself, so
        factoring here would do the cubic work twice per suggest."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).reshape(-1)
        self._raw_y = y
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        self._chol = None
        self._alpha = None
        self._x = x

    def _ensure_factor(self):
        if self._chol is not None:
            return
        yn = (self._raw_y - self._y_mean) / self._y_std
        k = self._kernel(self._x, self._x) + self.noise * np.eye(
            len(self._x)
        )
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn)
        )

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior (mean, std) at ``x`` in the ORIGINAL y units.
        Routes through the native implementation when available."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return (np.full(len(x), self._y_mean),
                    np.full(len(x), self._y_std))
        native = _native_predict(self, x)
        if native is not None:
            return native
        self._ensure_factor()
        ks = self._kernel(x, self._x)
        mu = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        # RBF prior variance is constant on the diagonal — no need to
        # build the full candidate kernel matrix
        var = np.clip(
            self.signal_variance - (v * v).sum(0), 1e-12, None
        )
        return (mu * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)


def _native_call(fn_name: str, gp: "GaussianProcess", cand, **extra):
    """Shared native-dispatch policy for the GP entry points: disabled
    by HVTPU_FORCE_PY_GP=1 or before fit; None (-> numpy twin fallback)
    when the toolchain is missing, the Gram matrix is singular, or the
    native call fails for any reason."""
    import os

    if (getattr(gp, "_raw_y", None) is None
            or os.environ.get("HVTPU_FORCE_PY_GP", "0") == "1"):
        return None
    try:
        from ..native import core as native_core

        fn = getattr(native_core, fn_name)
        return fn(
            gp._x, gp._raw_y, cand,
            length_scale=gp.length_scale, noise=gp.noise,
            signal_variance=gp.signal_variance, **extra,
        )
    except Exception:
        return None


def _native_predict(gp: "GaussianProcess", cand):
    """Posterior via native/src/gaussian_process.cc; None -> fall back
    to the numpy twin."""
    return _native_call("gp_predict", gp, cand)


_erf = np.vectorize(math.erf)


def _norm_pdf(z):
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


def _norm_cdf(z):
    return 0.5 * (1.0 + _erf(z / math.sqrt(2.0)))


def expected_improvement(gp: GaussianProcess, candidates: np.ndarray,
                         best_y: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (maximization; parity: the EI computation in
    bayesian_optimization.cc).  One native fit+predict+EI call when the
    library is available, numpy twin otherwise."""
    candidates = np.atleast_2d(np.asarray(candidates, np.float64))
    if gp._x is not None:
        ei = _native_call("gp_expected_improvement", gp, candidates,
                          best_y=best_y, xi=xi)
        if ei is not None:
            return ei
    mu, sigma = gp.predict(candidates)
    imp = mu - best_y - xi
    z = imp / sigma
    ei = imp * _norm_cdf(z) + sigma * _norm_pdf(z)
    ei[sigma < 1e-12] = 0.0
    return ei


class BayesianOptimizer:
    """Sequential maximizer over a box (parity: BayesianOptimization).

    Coordinates are normalized to [0, 1]^d; ``suggest`` returns the
    next point (seed points first, then argmax-EI over a random
    candidate cloud), ``observe`` records a score.
    """

    def __init__(self, bounds: List[Tuple[float, float]],
                 seed_points: Optional[List] = None,
                 n_candidates: int = 256, rng_seed: int = 0):
        self.bounds = np.asarray(bounds, np.float64)
        self._rng = np.random.RandomState(rng_seed)
        self._gp = GaussianProcess(length_scale=0.3, noise=1e-4)
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []
        self._seeds = [np.asarray(p, np.float64)
                       for p in (seed_points or [])]
        self._n_candidates = n_candidates

    def _to_unit(self, x):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return (np.asarray(x, np.float64) - lo) / (hi - lo)

    def _from_unit(self, u):
        lo, hi = self.bounds[:, 0], self.bounds[:, 1]
        return lo + np.asarray(u, np.float64) * (hi - lo)

    def suggest(self) -> np.ndarray:
        if len(self._xs) < len(self._seeds):
            return self._seeds[len(self._xs)]
        if not self._xs:
            return self._from_unit(self._rng.rand(len(self.bounds)))
        self._gp.fit(
            np.stack([self._to_unit(x) for x in self._xs]),
            np.asarray(self._ys),
        )
        cand = self._rng.rand(self._n_candidates, len(self.bounds))
        ei = expected_improvement(self._gp, cand, max(self._ys))
        return self._from_unit(cand[int(np.argmax(ei))])

    def observe(self, x, y: float):
        self._xs.append(np.asarray(x, np.float64))
        self._ys.append(float(y))

    @property
    def best_index(self) -> int:
        return int(np.argmax(self._ys))

    @property
    def best(self) -> Tuple[np.ndarray, float]:
        i = self.best_index
        return self._xs[i], self._ys[i]

    @property
    def num_observations(self) -> int:
        return len(self._ys)
