"""knob-registry fixture (bad): drift in every direction."""

import os

# Read but missing from docs/knobs.md -> undocumented-knob.
UNDOC = os.environ.get("HVTPU_FIXTURE_UNDOC", "0")

# Read and documented, but the doc row still says TODO -> describe:.
TODOKNOB = os.getenv("HVTPU_FIXTURE_TODO", "1")
