"""Worker-side elastic machinery: the ``@hvt.elastic.run`` decorator and
the driver-notification channel.

Parity surface: ``horovod/common/elastic.py`` (``run_fn``) and
``horovod/runner/elastic/worker.py`` (``WorkerNotificationManager``).

TPU-native mapping: the reference keeps worker processes alive across
membership changes and re-runs Gloo rendezvous in-process; the JAX
coordination service cannot do that, so reconfiguration is
**restart-based** (see elastic/state.py).  The decorator therefore
terminates the process with a dedicated exit code when the world must
change, and the elastic driver relaunches everyone; committed state is
reloaded through ``state.sync()`` in the fresh incarnation.  Driver →
worker notification rides SIGUSR1 instead of the reference's HTTP
notification service — same commit-boundary semantics.  (SIGUSR2 is
taken by the flight recorder for on-demand postmortem dumps — see
obs/flight.py and docs/robustness.md.)
"""

from __future__ import annotations

import functools
import os
import signal
import sys

import logging

from ..core import faults
from ..core import state as core_state
from ..core.retry import FENCE_EXIT_CODE  # noqa: F401  (re-export)
from ..core.exceptions import (DrainInterrupt, HorovodInternalError,
                               HostsUpdatedInterrupt)
from ..obs import flight
from ..obs import metrics as obs_metrics
from .state import State, _HostUpdateFlag

logger = logging.getLogger("horovod_tpu")

# Worker-side elastic telemetry (obs/metrics.py): reset requests by
# cause — the driver's restart counter says HOW OFTEN the world was
# rebuilt; this says WHY (peer crash vs planned membership change).
_M_RESETS = obs_metrics.counter(
    "hvtpu_elastic_worker_resets_total",
    "World-reset requests issued by this worker, by reason "
    "(collective_failure | hosts_updated | peer_drain).")
_M_SIGUSR1_FAILED = obs_metrics.counter(
    "hvtpu_elastic_sigusr1_install_failed_total",
    "Failed attempts to install the driver-notification (SIGUSR1) "
    "handler; membership changes then surface as driver-initiated "
    "restarts only.")

# Exit code the driver interprets as "re-rendezvous requested" (worker
# hit a recoverable elastic event); anything else non-zero is a crash.
# FENCE_EXIT_CODE (re-exported above from core/retry.py) is the third
# planned status: "this rank self-fenced" — superseded generation or
# expired KV lease — which the driver also must NOT count as a crash.
RESET_EXIT_CODE = 73


def _install_sigusr1_handler():
    """SIGUSR1 from the driver == 'hosts updated' (parity: the
    WorkerNotificationService HTTP callback setting the host flag)."""

    def handler(signum, frame):
        _HostUpdateFlag.instance().set()

    try:
        signal.signal(signal.SIGUSR1, handler)
    except ValueError:
        # non-main thread (e.g. tests importing under a runner thread):
        # notifications degrade to driver-initiated restarts only —
        # a real elastic job losing this channel is worth knowing
        # about, so say so instead of degrading silently.
        _M_SIGUSR1_FAILED.inc()
        logger.warning(
            "could not install the SIGUSR1 host-update handler "
            "(signal.signal outside the main thread); driver "
            "membership notifications degrade to SIGUSR1-kill -> "
            "restart instead of commit-boundary resets")


def note_step() -> None:
    """The ``worker.step`` fault-injection site (core/faults.py),
    invoked by ``State.commit()`` at every commit boundary — the
    canonical 'step' of an elastic loop.  A ``kill`` clause here
    reproduces the worker-dies-mid-training scenario the driver's
    recovery loop exists for; the empty-spec cost is one attribute
    read."""
    if faults.ACTIVE:
        faults.inject("worker.step")


def run(func):
    """Decorator for elastic training functions (parity:
    ``hvd.elastic.run`` / run_fn).

    Usage::

        @hvt.elastic.run
        def train(state, ...):
            while state.epoch < epochs:
                ...
                state.commit()

    On ``HorovodInternalError`` (a peer died mid-collective) the state
    rolls back to the last commit and the process exits with
    RESET_EXIT_CODE so the driver rebuilds the world; on
    ``HostsUpdatedInterrupt`` (driver signalled membership change) the
    current (committed) state stands and the process exits likewise.
    In the relaunched incarnation ``state.sync()`` restores progress
    from the durable commit.
    """

    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        _install_sigusr1_handler()
        if not core_state.initialized():
            raise RuntimeError(
                "hvt.init() must be called before an elastic run"
            )
        try:
            state.sync()
            if os.environ.get("HVTPU_ELASTIC_GENERATION", "0") != "0":
                # Relaunched incarnation after a world change: run the
                # user's reset callbacks AFTER sync restored the
                # committed state, so world-size-derived values they
                # rebuild (lr schedules etc.) are not clobbered by the
                # old world's committed copy.  Parity:
                # horovod/common/elastic.py run_fn's state.on_reset()
                # between reset() and the next sync — same net order
                # (callbacks see the new world, then training resumes).
                state.on_reset()
                # Callbacks may be rank-dependent (anything derived
                # from hvd.rank()); a broadcast-only re-sync makes the
                # tracked attributes identical again before training
                # resumes — the reference achieves the same by running
                # callbacks before its sync.
                state.rebroadcast()
            # Verified-identical incarnation start: with the parameter
            # divergence audit enabled (HVTPU_AUDIT_EVERY > 0), prove
            # every rank resumed from the same bytes BEFORE training
            # touches them — a divergence here aborts into the
            # restore/relaunch path below instead of training on
            # silently split replicas (core/audit.py).
            state.audit("elastic.sync")
            return func(state, *args, **kwargs)
        except HorovodInternalError:
            # Peer loss mid-collective: roll back so the durable commit
            # reflects the last good step, then ask for a new world.
            _M_RESETS.inc(reason="collective_failure")
            if flight.ACTIVE:
                flight.note("worker_reset", reason="collective_failure")
            state.restore()
            _exit_for_reset("collective failure")
        except DrainInterrupt as e:
            # A peer drained after a preemption notice
            # (core/preempt.py): the drain commit already persisted
            # this step, so NO restore — the next incarnation resumes
            # from it with zero lost steps.  Must precede the parent
            # HostsUpdatedInterrupt handler.
            _M_RESETS.inc(reason="peer_drain")
            if flight.ACTIVE:
                flight.note("worker_reset", reason="peer_drain",
                            peer=e.rank)
            _exit_for_reset(
                f"peer drain (rank {e.rank} departing, planned)")
        except HostsUpdatedInterrupt:
            _M_RESETS.inc(reason="hosts_updated")
            if flight.ACTIVE:
                flight.note("worker_reset", reason="hosts_updated")
            _exit_for_reset("hosts updated")
        except BaseException as e:
            # Unhandled user/runtime exception: this process is about
            # to die on a path nobody anticipated — exactly what the
            # black box exists for.  Dump, then re-raise untouched.
            if not isinstance(e, SystemExit) or (e.code or 0) != 0:
                if flight.ACTIVE:
                    flight.note("worker_exception",
                                error=type(e).__name__,
                                detail=str(e)[:300])
                flight.dump_postmortem(
                    "unhandled_exception", error=type(e).__name__)
            raise

    return wrapper


def _exit_for_reset(reason: str):
    print(
        f"hvtpu.elastic: requesting world reset ({reason}); "
        f"exiting {RESET_EXIT_CODE} for driver relaunch",
        file=sys.stderr,
        flush=True,
    )
    # os._exit skips atexit hooks: flush queued background checkpoint
    # writes now or the last durable commit may never reach disk.
    try:
        from ..core import durable as core_durable

        core_durable.quiesce_writers()
    except Exception:
        pass
    try:
        core_state.shutdown()
    except Exception:
        pass
    # os._exit: the coordination client's channels may be wedged (peer
    # death); a normal exit could hang in atexit grpc teardown.
    sys.stderr.flush()
    sys.stdout.flush()
    os._exit(RESET_EXIT_CODE)
