"""Stall watchdog for the SYNC eager data plane.

Parity surface: ``horovod/common/stall_inspector.cc``
(``StallInspector::CheckForStalledTensors`` /
``InvalidateStalledCachedResponses``) — the reference's coordinator
names every tensor some rank has submitted that others haven't, warns
after ``HOROVOD_STALL_CHECK_TIME_SECONDS`` and aborts after
``HOROVOD_STALL_SHUTDOWN_TIME_SECONDS``.  The *async* path here has
its own inspector inside the eager mini-controller
(``eager/controller.py``); this module covers the **sync** ops in
``comm/eager.py``, which otherwise enter an XLA collective that simply
blocks forever when a rank diverges — the classic Horovod deadlock
this subsystem exists to catch (SURVEY §5.2 calls it essential).

Two modes (``HVTPU_STALL_CHECK_MODE``):

**amortized** (default) — the reference's own cost model: its
inspector piggybacks the coordinator's existing cycle, adding ~zero
per-op traffic.  Here every sync collective does LOCAL bookkeeping
only (a per-process-set sequence counter, a bounded ring of recent op
descriptors, an in-flight marker); a background heartbeat thread
publishes the snapshot to the coordination KV every
``stall_heartbeat_seconds`` (default 0.5) and reads the peers':

- a peer's ring holding a DIFFERENT descriptor at a shared sequence
  number → the ranks diverged onto different collectives → fail,
  naming both ops and the op index;
- this rank in-flight past ``stall_check_time_seconds`` while member
  ranks' counters never reached the op → warn, naming exactly which
  ranks are absent (repeats each interval);
- past ``stall_shutdown_time_seconds`` (when > 0) → fail.

"Fail" latches a diagnosis that the data plane raises as
``HorovodInternalError`` wherever it can do so cleanly: the next op's
pre-dispatch check, or the interruptible completion wait
(``wait_ready`` polls ``jax.Array.is_ready`` instead of parking inside
an uninterruptible XLA wait, so even a rank already blocked on a
doomed collective aborts with the diagnosis).  The elastic ``run``
decorator catches that error as a recoverable failure, so a stalled
elastic job rolls back and re-rendezvouses like the reference's
shutdown-on-stall path.  Detection latency is one heartbeat; the
healthy-path cost is ~1 µs/op and two KV RPCs per heartbeat — vs one
KV write plus a polled read PER OP for strict mode, which doubled
small-op latency (BENCH_SCALING.json coordination_vs_P, round 4).

**strict** — the round-4 pre-dispatch rendezvous: post
``stall/<gen>/<set>/<seq>/<rank> = op-descriptor``, await every member
rank's mark before dispatching, diagnose mismatches immediately.  No
collective is ever dispatched unless all members confirmed the same
descriptor — useful when debugging a desync that corrupts instead of
deadlocks — at the price of a KV round-trip per op.

The async controller's cycle thread executes its (already negotiated)
responses through the same ``comm/eager`` functions; it registers
itself via ``bypass_thread()`` so those dispatches skip the watchdog.
Nested internal collectives (allgather's size negotiation, alltoall's
split exchange) are part of the op's implementation, hence identical
on every rank, so their extra bookkeeping stays consistent and only
refines diagnostics.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
from collections import deque
from typing import Dict, List, Optional

from ..core import clock
from ..core import faults
from ..core import preempt
from ..core.exceptions import HorovodInternalError
from ..obs import flight
from ..obs import tracing
from ..obs import metrics as obs_metrics
from . import wirefault

logger = logging.getLogger("horovod_tpu")

# Watchdog telemetry (obs/metrics.py; catalog in docs/observability.md).
_M_HB_AGE = obs_metrics.gauge(
    "hvtpu_stall_heartbeat_age_seconds",
    "Age of the most-stale live peer heartbeat (amortized mode); a "
    "climbing value means a peer stopped beating.")
_M_WARNINGS = obs_metrics.counter(
    "hvtpu_stall_warnings_total", "Stall warnings emitted.")
_M_ABORTS = obs_metrics.counter(
    "hvtpu_stall_aborts_total",
    "Stall/mismatch failures latched or raised (job-fatal).")
_M_SUSPECT_S = obs_metrics.histogram(
    "hvtpu_partition_suspect_seconds",
    "How long silent peers spent in the partitioned-suspect state "
    "(stall blame held) before recovering or being declared dead; "
    "observed at resolution.")

_NS = "hvtstall"      # strict-mode per-op rendezvous marks
_HB = "hvtstallhb"    # amortized-mode heartbeat snapshots
_tls = threading.local()

_RING = 256           # per-set descriptor history kept locally
_POST = 48            # ring tail published in each heartbeat

# Latched when the watchdog abandons a PENDING collective (wait_ready
# raised while the op never completed): the XLA runtime then holds an
# execution thread parked inside the dead collective, so a normal
# interpreter teardown (client destructor, jax.distributed shutdown
# barrier) would hang.  Exit paths consult this to hard-exit instead
# — the reference's stall shutdown likewise aborts the process.
_poisoned = False
_poison_gen = -1


def _latch_poison(insp) -> None:
    """Latch only for the INSTALLED inspector: a standalone instance
    (unit tests, tooling) abandoning a fake wait must not hijack the
    whole interpreter's exit path."""
    global _poisoned, _poison_gen
    try:
        from ..core import state as _core_state

        if _core_state.global_state().sync_stall is not insp:
            return
    except Exception:
        return
    _poisoned = True
    _poison_gen = max(_poison_gen, insp.gen)


def poisoned() -> bool:
    """True when a stall/mismatch abort left a stuck collective behind
    and process teardown must not wait on the XLA runtime."""
    return _poisoned


def poison_exit_status() -> int:
    """Exit status for the hard-exit path: 0 when the process
    re-initialized into a NEWER generation after the poisoning (the
    wedged execution belongs to a previous session — e.g. elastic
    recovery rolled back and the job went on to finish).  Otherwise
    the stall abort is the terminal event: an ELASTIC job exits with
    ``RESET_EXIT_CODE`` so the driver feeds the death into its
    recovery loop (rollback + relaunch) instead of scoring a crash
    strike against a healthy host; a non-elastic job keeps the hard
    abort (1)."""
    try:
        from ..core import state as _core_state

        if _core_state.global_state().init_generation > _poison_gen:
            return 0
    except Exception:
        pass
    if _elastic_job():
        from ..elastic.worker import RESET_EXIT_CODE

        return RESET_EXIT_CODE
    return 1


def _elastic_job() -> bool:
    """True when this process belongs to an elastic job — from the live
    config when initialized, else from the driver-exported env (the
    atexit path runs after shutdown() cleared the config)."""
    import os as _os

    try:
        from ..core import state as _core_state

        cfg = _core_state.global_state().config
        if cfg is not None:
            return bool(cfg.elastic)
    except Exception:
        pass
    return str(_os.environ.get("HVTPU_ELASTIC", "")).strip().lower() in (
        "1", "true", "yes", "on")


def _reset_poison() -> None:
    """Clear the latch (test hook: the poison state is process-global
    and would otherwise leak a hard-exit into unrelated tests)."""
    global _poisoned, _poison_gen
    _poisoned = False
    _poison_gen = -1


def _mismatch_msg(set_id, seq, rank, mine, peer, theirs) -> str:
    return (
        f"collective mismatch at process set {set_id} op #{seq}: this "
        f"rank ({rank}) entered [{mine}] but rank {peer} entered "
        f"[{theirs}]. Ranks have diverged onto different collectives; "
        "this would deadlock or corrupt the wire."
    )


def _stall_abort_msg(desc, set_id, seq, elapsed, abort_s, pending) -> str:
    return (
        f"stalled collective [{desc}] (process set {set_id}, op "
        f"#{seq}): waited {elapsed:.1f}s > stall shutdown time "
        f"{abort_s:.1f}s; ranks not at the rendezvous: {pending}. One "
        "or more ranks skipped this collective or died before "
        "reaching it."
    )


def bypass_thread():
    """Mark the CURRENT thread's eager collectives as exempt from the
    sync watchdog (used by the async controller's cycle thread, whose
    op order is already negotiated and stall-inspected)."""
    _tls.bypass = True


def bypass_active() -> bool:
    """True on threads marked by :func:`bypass_thread`.  The sync data
    plane consults this before opening its own trace span — controller-
    driven dispatches are already spanned by the controller's
    NEGOTIATE/QUEUE/EXEC phases and must not double-trace."""
    return bool(getattr(_tls, "bypass", False))


class SyncStallInspector:
    """Strict mode: per-op rendezvous over the coordination KV."""

    def __init__(self, client, rank: int, warn_s: float, abort_s: float,
                 generation: int = 0):
        self._kv = client
        self.rank = rank
        self.warn_s = warn_s
        self.abort_s = abort_s
        self.gen = generation
        self._seq: Dict[int, int] = {}

    def debug_state(self) -> dict:
        """/debug provider payload (strict mode has no heartbeats; the
        per-set sequence counters are the useful live signal)."""
        return {
            "mode": "strict",
            "rank": self.rank,
            "generation": self.gen,
            "op_seq_per_set": {str(k): v for k, v in self._seq.items()},
        }

    # -- key helpers --------------------------------------------------
    def _key(self, set_id: int, seq: int, rank: int) -> str:
        return f"{_NS}/{self.gen}/{set_id}/{seq}/{rank}"

    def _try_get(self, key: str) -> Optional[str]:
        try:
            return self._kv.key_value_try_get(key)
        except Exception:
            return None

    def _marks(self, set_id: int, seq: int) -> Optional[Dict[int, str]]:
        """All posted marks for (set, seq) in ONE RPC via the KV's
        directory get — the happy path costs one roundtrip regardless
        of P.  Returns None when the client has no usable dir-get
        (test fakes, older clients), so the caller can fall back to
        per-rank try_get; {} means 'working, nothing posted yet'."""
        prefix = f"{_NS}/{self.gen}/{set_id}/{seq}/"
        dir_get = getattr(self._kv, "key_value_dir_get", None)
        if dir_get is None:
            return None
        try:
            return {int(k.rsplit("/", 1)[-1]): v
                    for k, v in dir_get(prefix)}
        except Exception:
            return None

    # -- the rendezvous -----------------------------------------------
    def rendezvous(self, set_id: int, member_ranks, desc: str):
        """Block until every member rank posts a mark for this set's
        next sequence number; warn/abort on deadline."""
        seq = self._seq.get(set_id, 0)
        self._seq[set_id] = seq + 1
        self._kv.key_value_set(self._key(set_id, seq, self.rank), desc)

        pending = [r for r in member_ranks if r != self.rank]
        start = clock.monotonic()
        next_warn = self.warn_s
        sleep = 0.0
        use_dir = True
        while pending:
            found = self._marks(set_id, seq) if use_dir else None
            if found is None:
                use_dir = False
                found = {}
                for r in pending:
                    val = self._try_get(self._key(set_id, seq, r))
                    if val is not None:
                        found[r] = val
            still = []
            for r in pending:
                val = found.get(r)
                if val is None:
                    still.append(r)
                elif val != desc:
                    _M_ABORTS.inc()
                    if flight.ACTIVE:
                        flight.note("collective_mismatch", collective=desc,
                                    process_set=set_id, op_seq=seq,
                                    peer_rank=r, peer_desc=val)
                    flight.dump_postmortem(
                        "collective_mismatch", collective=desc,
                        peer_rank=r)
                    raise HorovodInternalError(
                        _mismatch_msg(set_id, seq, self.rank, desc,
                                      r, val))
            pending = still
            if not pending:
                break
            elapsed = clock.monotonic() - start
            # A rank inside its drain grace window (core/preempt.py) is
            # late BY DESIGN — it is heading for the drain commit, not
            # stuck.  Hold the abort and report it as draining; once
            # the window expires, draining_ranks() empties and normal
            # abort semantics resume.
            draining = preempt.draining_ranks() if preempt.pending() \
                else {}
            blamable = [r for r in pending if r not in draining]
            if self.abort_s > 0 and elapsed > self.abort_s and blamable:
                _M_ABORTS.inc()
                if flight.ACTIVE:
                    flight.note("stall_abort", collective=desc,
                                process_set=set_id, op_seq=seq,
                                waited_s=round(elapsed, 3),
                                ranks_missing=sorted(blamable))
                flight.dump_postmortem(
                    "stall_abort", collective=desc,
                    ranks_missing=sorted(blamable))
                raise HorovodInternalError(
                    _stall_abort_msg(desc, set_id, seq, elapsed,
                                     self.abort_s, blamable))
            if self.warn_s > 0 and elapsed > next_warn and not blamable:
                next_warn += self.warn_s
                for r in sorted(r for r in pending if r in draining):
                    logger.info(
                        "rank %d draining (%.0fs grace remaining); "
                        "holding the stall abort for [%s] "
                        "(process set %s, op #%d)",
                        r, draining.get(r, 0.0), desc, set_id, seq)
            elif self.warn_s > 0 and elapsed > next_warn:
                next_warn += self.warn_s
                _M_WARNINGS.inc()
                logger.warning(
                    "stalled collective [%s] (process set %s, op #%d): "
                    "waited %.1fs; ranks not at the rendezvous: %s",
                    desc, set_id, seq, elapsed, blamable,
                )
                if tracing.ACTIVE:
                    tracing.instant(
                        "stall_warning", collective=desc,
                        process_set=set_id, op_seq=seq,
                        waited_s=elapsed, ranks_missing=sorted(blamable))
                if flight.ACTIVE:
                    flight.note("stall_warning", collective=desc,
                                process_set=set_id, op_seq=seq,
                                waited_s=round(elapsed, 3),
                                ranks_missing=sorted(blamable))
            # back off from a near-spin (normal skew is sub-ms) to a
            # 20ms poll for genuinely late peers
            sleep = min(0.02, sleep * 2 if sleep else 0.0002)
            clock.sleep(sleep)

        # rolling cleanup: every member has posted seq, so nobody can
        # still be waiting on marks older than seq — drop our own
        # previous mark to keep the KV bounded (each rank deletes only
        # its own keys; no cross-rank races)
        if seq > 0:
            try:
                self._kv.key_value_delete(
                    self._key(set_id, seq - 1, self.rank))
            except Exception:
                pass


class _SetTrack:
    """Per-process-set local bookkeeping (amortized mode)."""

    __slots__ = ("seq", "ring", "inflight", "t0", "members", "next_warn")

    def __init__(self):
        self.seq = 0                      # ops STARTED on this set
        # (seq, descriptor, start time) history
        self.ring = deque(maxlen=_RING)
        self.inflight: Optional[str] = None
        self.t0 = 0.0
        self.members: tuple = ()
        self.next_warn = 0.0


class AmortizedStallInspector:
    """Amortized mode: local bookkeeping + background heartbeat.

    See the module docstring for the protocol.  All state shared with
    the heartbeat thread lives behind ``_lock``; the data-plane hooks
    (``pre_op``/``wait_ready``) never perform KV RPCs.
    """

    def __init__(self, client, rank: int, warn_s: float, abort_s: float,
                 heartbeat_s: float = 0.5, generation: int = 0,
                 stale_s: Optional[float] = None,
                 suspect_s: Optional[float] = None,
                 start_heartbeat: bool = True):
        self._kv = client
        self.rank = rank
        self.warn_s = warn_s
        self.abort_s = abort_s
        self.heartbeat_s = max(heartbeat_s, 0.02)
        # a peer whose beat number stops advancing for this long is
        # treated as dead/stalled even if its last snapshot showed it
        # caught up — it may have died MID-collective, after posting
        self.stale_s = (max(5 * self.heartbeat_s, 2.0)
                        if stale_s is None else stale_s)
        # partitioned-vs-dead classification (HVTPU_PARTITION_SUSPECT_S,
        # default 0 = off): a peer stale for (stale_s, stale_s +
        # suspect_s] is a partition SUSPECT — silent because it may be
        # cut off from the KV, not dead — and stall blame is held while
        # it either recovers or self-fences on its own lease
        # (core/retry.py FencedKV).  Past the suspect window it is
        # classified dead and blamed normally.
        self.suspect_s = (
            float(os.environ.get("HVTPU_PARTITION_SUSPECT_S", "0") or 0)
            if suspect_s is None else suspect_s)
        # rank -> when it entered the suspect state; touched only from
        # the heartbeat thread
        self._suspected: Dict[int, float] = {}
        # rank -> (last beat number, when it last changed); touched
        # only from the heartbeat thread
        self._peer_seen: Dict[int, tuple] = {}
        # per-peer wire-link health folded out of this same heartbeat
        # stream (comm/wirefault.py): the beat thread writes arrival
        # gaps / losses, the data plane and /debug read scores
        self.link_health = wirefault.LinkHealth(
            expect_s=max(heartbeat_s, 0.02))
        # lazily-created abort-retry consensus (comm/wirefault.py)
        self._wire_consensus = None
        self.gen = generation
        self._lock = threading.Lock()
        self._tracks: Dict[str, _SetTrack] = {}
        self.failure: Optional[str] = None
        self._beat = 0
        self._stopped = threading.Event()
        # Collective dispatch executor: some backends execute the
        # compiled program synchronously ON the dispatching thread
        # (CPU/Gloo runs the wire exchange inline), which would park
        # the main thread uninterruptibly inside a dead collective.
        # Dispatching from this helper thread keeps the main thread
        # free to observe the failure latch and raise.
        self._exec_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._exec_thread: Optional[threading.Thread] = None
        # start_heartbeat=False (fabric simulator): no background
        # thread — the sim pumps _beat_once() itself on virtual time.
        self._thread: Optional[threading.Thread] = None
        if start_heartbeat:
            self._thread = threading.Thread(
                target=self._beat_loop, name="hvt-stall-heartbeat",
                daemon=True)
            self._thread.start()

    # -- data-plane hooks (hot path: no RPCs) --------------------------
    def pre_op(self, set_id, members, desc: str) -> str:
        """Record the op start and return the descriptor (callers
        thread it to ``finish``/``wait_ready`` for re-arm naming);
        raise a latched failure cleanly before dispatching another
        doomed collective."""
        with self._lock:
            if self.failure:
                raise HorovodInternalError(self.failure)
            tr = self._tracks.get(str(set_id))
            if tr is None:
                tr = self._tracks[str(set_id)] = _SetTrack()
            tr.members = tuple(members)
            now = clock.monotonic()
            tr.ring.append((tr.seq, desc, now))
            tr.inflight = desc
            tr.t0 = now
            tr.next_warn = self.warn_s
            tr.seq += 1
        return desc

    def _rearm(self, set_id, desc: Optional[str]) -> None:
        """Re-arm the in-flight marker after a nested negotiation
        collective cleared it — under the OUTER op's descriptor and
        ORIGINAL start time (found in the ring), so a stall during
        the main wire exchange is diagnosed as the op the user
        called, with its true age."""
        with self._lock:
            tr = self._tracks.get(str(set_id))
            if tr is None or tr.inflight is not None or not tr.ring:
                return
            entry = None
            if desc is not None:
                for e in reversed(tr.ring):
                    if e[1] == desc:
                        entry = e
                        break
            if entry is None:
                entry = tr.ring[-1]
            tr.inflight = entry[1]
            tr.t0 = entry[2]
            tr.next_warn = self.warn_s

    def dispatch(self, set_id, fn, args, desc: Optional[str] = None):
        """Run ``fn(*args)`` (a compiled collective) on the executor
        thread; wait interruptibly so a latched failure aborts this
        rank even when the backend executes synchronously on the
        dispatching thread.  On abort the executor stays parked inside
        the dead collective — the process is poisoned (see
        ``poisoned()``) and exit paths hard-exit.  Returns
        ``(result, pending)`` where ``pending`` is True when the
        result was still in flight the moment ``fn`` returned
        (sampled on the executor thread, before handoff latency can
        hide it) — the caller's async-dispatch proof.  Every error
        path clears the in-flight marker: a failed attempt must never
        trip a later healthy op into a false stall abort (a retry
        re-arms the marker from the ring via ``_rearm``)."""
        with self._lock:
            if self.failure:
                tr = self._tracks.get(str(set_id))
                if tr is not None:
                    tr.inflight = None
                raise HorovodInternalError(self.failure)
        # Fault site ``collective.exec``: the attempt dies on this
        # rank's dispatching thread after bytes may already be in
        # flight — transport-shaped, so the wire retry loop in the
        # module-level ``dispatch`` classifies it as a mid-flight
        # failure (never eligible for a late join).
        if faults.ACTIVE and faults.inject("collective.exec"):
            self._clear_inflight(set_id)
            raise ConnectionError(
                "Connection reset: injected collective.exec fault")
        if self._exec_thread is None or not self._exec_thread.is_alive():
            self._exec_thread = threading.Thread(
                target=self._exec_loop, name="hvt-stall-dispatch",
                daemon=True)
            self._exec_thread.start()
        self._rearm(set_id, desc)
        # done, value, error, pending-at-return
        box = [threading.Event(), None, None, False]
        self._exec_q.put((box, fn, args))
        while not box[0].wait(0.05):
            if self.failure:
                _latch_poison(self)
                self._clear_inflight(set_id)
                # the executor is wedged inside the dead collective;
                # leave it (daemon) and surface the diagnosis
                self._exec_thread = None
                raise HorovodInternalError(self.failure)
        if box[2] is not None:
            # the attempt failed on the executor thread: drop the
            # in-flight marker before surfacing (leak here meant the
            # NEXT healthy op inherited a stale marker and aged into
            # a false stall abort)
            self._clear_inflight(set_id)
            raise box[2]
        return box[1], box[3]

    def _exec_loop(self) -> None:
        while True:
            item = self._exec_q.get()
            if item is None:
                return
            box, fn, args = item
            try:
                box[1] = fn(*args)
                # sample async-ness HERE, before handoff latency lets
                # a fast collective finish and hide the evidence
                box[3] = _pending_leaf(box[1])
            except BaseException as e:  # surfaced on the caller thread
                box[2] = e
            finally:
                box[0].set()

    def wait_ready(self, set_id, out, desc: Optional[str] = None) -> None:
        """Interruptible completion wait: poll ``is_ready`` so the
        heartbeat's failure latch can abort a rank that would otherwise
        park forever inside XLA's blocking wait.  Completes in one
        check on the healthy path once the result lands.  ``desc``
        names the op being waited on (for re-arming after a nested
        negotiation collective cleared the in-flight marker)."""
        is_ready = getattr(out, "is_ready", None)
        self._rearm(set_id, desc)
        sleep = 0.0
        waited = 0.0
        try:
            while is_ready is not None and not is_ready():
                if self.failure:
                    _latch_poison(self)
                    raise HorovodInternalError(self.failure)
                # back off from a near-spin (small ops land in <1 ms)
                # to a 0.5 ms poll, then to 5 ms once the op has
                # clearly left the small-op regime — bounds both the
                # overshoot (sub-1% of the op at every scale) and the
                # poll rate
                waited += sleep
                cap = 5e-4 if waited < 0.02 else 5e-3
                sleep = min(cap, sleep * 2 if sleep else 5e-5)
                clock.sleep(sleep)
        finally:
            # also on error paths (``is_ready`` raising, the latch
            # above): a stale marker would trip a later healthy op
            # into a false stall abort
            self._clear_inflight(set_id)
        if self.failure:
            # the collective completed but the job is already failed
            # (e.g. a peer diverged on another set) — surface it now
            raise HorovodInternalError(self.failure)

    def _clear_inflight(self, set_id) -> None:
        with self._lock:
            tr = self._tracks.get(str(set_id))
            if tr is not None:
                tr.inflight = None

    def op_info(self, set_id, desc: Optional[str] = None):
        """``(seq, members, desc)`` of the newest op on this set — the
        wire-consensus identity of a failed attempt (``desc`` falls
        back to the in-flight marker, then the ring tail, because a
        failed attempt already cleared the marker)."""
        with self._lock:
            tr = self._tracks.get(str(set_id))
            if tr is None:
                return 0, (), desc or ""
            d = desc or tr.inflight
            if d is None and tr.ring:
                d = tr.ring[-1][1]
            return tr.seq - 1, tr.members, d or ""

    def wire_consensus(self):
        """This rank's abort-retry consensus over the same fenced KV
        and heartbeat namespace the watchdog already uses (lazy: jobs
        with retries disabled never touch it)."""
        c = self._wire_consensus
        if c is None:
            c = self._wire_consensus = wirefault.WireConsensus(
                self._kv, self.rank, generation=self.gen,
                hb_prefix=f"{_HB}/{self.gen}/")
        return c

    def debug_state(self) -> dict:
        """/debug provider payload: per-peer heartbeat ages (seconds
        since each peer's beat number last advanced).  _peer_seen is
        written only by the heartbeat thread; the snapshot below is an
        intentional racy read of a dict whose values are immutable
        tuples."""
        now = clock.monotonic()
        ages = {str(r): round(now - t, 3)
                for r, (_b, t) in list(self._peer_seen.items())}
        return {
            "mode": "amortized",
            "rank": self.rank,
            "generation": self.gen,
            "heartbeat_s": self.heartbeat_s,
            "stale_s": self.stale_s,
            "suspect_s": self.suspect_s,
            "partition_suspects": sorted(self._suspected),
            "peer_heartbeat_age_s": ages,
            "link_health": self.link_health.snapshot(),
            "failure": self.failure,
        }

    def stop(self) -> None:
        self._stopped.set()
        if self._exec_thread is not None and self._exec_thread.is_alive():
            self._exec_q.put(None)
            self._exec_thread.join(timeout=2.0)
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        # a goodbye tombstone, NOT a plain delete: peers must be able
        # to tell a clean exit (don't blame this rank for a stall —
        # e.g. a stall_guard(block=False) marker legitimately left
        # armed after the final step) from a death (do).  It CARRIES
        # any latched failure: an aborting rank usually stops before
        # its next scheduled beat, and without this the peers would
        # never learn the diagnosis — they'd hang in the next
        # collective and die on the torn-down transport instead.
        try:
            # beat+1: strictly greater than any beat a wedged
            # heartbeat thread might still post, so the tombstone
            # always wins the latest-beat selection
            self._kv.key_value_set(
                f"{_HB}/{self.gen}/{self.rank}/{self._beat + 1}",
                json.dumps({"bye": True, "fail": self.failure,
                            "sets": {}}))
        except Exception:
            pass
        for b in (self._beat - 1, self._beat - 2):
            if b >= 0:
                try:
                    self._kv.key_value_delete(
                        f"{_HB}/{self.gen}/{self.rank}/{b}")
                except Exception:
                    pass

    # -- heartbeat -----------------------------------------------------
    def _beat_loop(self) -> None:
        while not self._stopped.wait(self.heartbeat_s):
            try:
                self._beat_once()
            except Exception:
                # the watchdog must never take the job down on its own
                logger.debug("stall heartbeat error", exc_info=True)

    def _beat_once(self) -> None:
        # Fault site ``heartbeat``: drop suppresses this beat entirely
        # (peers see this rank going stale — a wedged heartbeat
        # thread), delay lags it, error rides the _beat_loop catch,
        # kill simulates dying between collectives.
        if faults.ACTIVE and faults.inject("heartbeat"):
            return
        with self._lock:
            now = clock.monotonic()
            sets = {
                sid: {
                    "seq": tr.seq,
                    "ring": [[s, d] for s, d, _t in list(tr.ring)[-_POST:]],
                    "inflight": tr.inflight,
                    "age": (now - tr.t0) if tr.inflight else 0.0,
                }
                for sid, tr in self._tracks.items()
            }
            payload = json.dumps({"fail": self.failure, "sets": sets})
        key = f"{_HB}/{self.gen}/{self.rank}/{self._beat}"
        self._kv.key_value_set(key, payload)
        if self._beat >= 2:
            # rolling cleanup: each rank deletes only its own old beats
            try:
                self._kv.key_value_delete(
                    f"{_HB}/{self.gen}/{self.rank}/{self._beat - 2}")
            except Exception:
                pass
        self._beat += 1
        try:
            entries = self._kv.key_value_dir_get(f"{_HB}/{self.gen}/")
        except Exception:
            return
        latest: Dict[int, tuple] = {}
        for k, v in entries:
            parts = k.rsplit("/", 2)
            if len(parts) < 3:
                continue
            try:
                r, b = int(parts[-2]), int(parts[-1])
            except ValueError:
                continue
            if r == self.rank:
                continue
            if r not in latest or b > latest[r][0]:
                latest[r] = (b, v)
        now = clock.monotonic()
        lh = self.link_health
        for r, (b, _v) in latest.items():
            prev = self._peer_seen.get(r)
            if prev is None:
                self._peer_seen[r] = (b, now)
            elif b != prev[0]:
                # beat advanced: the per-beat arrival gap feeds the
                # link latency EWMA; beats skipped in between count
                # as losses (posted but never seen live)
                lh.observe(r, gap_s=(now - prev[1]) / max(1, b - prev[0]))
                for _ in range(min(3, b - prev[0] - 1)):
                    lh.observe(r, lost=True)
                self._peer_seen[r] = (b, now)
            elif now - prev[1] > 2 * self.heartbeat_s:
                # overdue with no new beat (a flapping link drops
                # them outright): one loss observation per own beat
                # until the peer recovers or goes stale
                lh.observe(r, lost=True)
        lh.publish()
        _M_HB_AGE.set(max(
            (now - t for _b, t in self._peer_seen.values()),
            default=0.0))
        peers: Dict[int, dict] = {}
        bye = set()
        bye_fails = []
        for r, (_b, v) in latest.items():
            try:
                snap = json.loads(v)
            except Exception:
                continue
            if snap.get("bye"):
                bye.add(r)
                if snap.get("fail"):
                    bye_fails.append((r, snap["fail"]))
            else:
                peers[r] = snap
        stale = {r for r, (_b, t) in self._peer_seen.items()
                 if r not in bye and now - t > self.stale_s}
        suspect = set()
        if self.suspect_s > 0:
            # partitioned-vs-dead split by lease age: freshly-stale
            # peers are SUSPECTS (blame held), peers silent past the
            # suspect window are dead (blamed normally)
            for r in list(stale):
                if now - self._peer_seen[r][1] <= (self.stale_s
                                                   + self.suspect_s):
                    suspect.add(r)
            stale -= suspect
            for r in suspect:
                if r not in self._suspected:
                    self._suspected[r] = now
                    logger.warning(
                        "rank %d heartbeat silent %.1fs: partition "
                        "suspect — holding stall blame for %.1fs",
                        r, now - self._peer_seen[r][1], self.suspect_s)
                    if flight.ACTIVE:
                        flight.note("partition_suspect", rank=self.rank,
                                    peer=r)
            for r in list(self._suspected):
                if r not in suspect:
                    _M_SUSPECT_S.observe(now - self._suspected.pop(r))
                    outcome = "dead" if r in stale else "recovered"
                    logger.info("rank %d left the partition-suspect "
                                "state: %s", r, outcome)
                    if flight.ACTIVE:
                        flight.note("partition_resolved",
                                    rank=self.rank, peer=r,
                                    outcome=outcome)
        self._evaluate(peers, stale, bye, bye_fails, suspect=suspect)

    def _evaluate(self, peers: Dict[int, dict],
                  stale: Optional[set] = None,
                  bye: Optional[set] = None,
                  bye_fails: Optional[list] = None,
                  suspect: Optional[set] = None) -> None:
        stale = stale or set()
        bye = bye or set()
        suspect = suspect or set()
        now = clock.monotonic()
        fail: Optional[str] = None
        warns: List[tuple] = []
        drain_notes: List[tuple] = []
        with self._lock:
            if self.failure:
                return
            # a peer that already latched a failure takes the whole job
            # down (reference shutdown-on-stall semantics): surface its
            # diagnosis instead of hanging on our side — including a
            # peer that already STOPPED, whose tombstone carries it
            for r, pf in (bye_fails or []):
                fail = f"rank {r} aborted the job: {pf}"
                break
            if not fail:
                for r, snap in peers.items():
                    pf = snap.get("fail")
                    if pf:
                        fail = f"rank {r} aborted the job: {pf}"
                        break
            for sid, tr in self._tracks.items():
                if fail:
                    break
                mine = {s: d for s, d, _t in tr.ring}
                for r, snap in peers.items():
                    pset = snap.get("sets", {}).get(sid)
                    if not pset:
                        continue
                    # divergence: a shared sequence number whose
                    # descriptor differs — the rings are seq-ordered,
                    # so the first hit is the earliest visible one
                    for s_d in pset.get("ring", []):
                        s, d = s_d[0], s_d[1]
                        md = mine.get(s)
                        if md is not None and md != d:
                            fail = _mismatch_msg(
                                sid, s, self.rank, md, r, d)
                            break
                    if fail:
                        break
                if fail:
                    break
                # stall: we are in-flight past the deadline and some
                # member's counter never reached this op
                if tr.inflight and tr.members:
                    age = now - tr.t0
                    want_abort = self.abort_s > 0 and age > self.abort_s
                    want_warn = self.warn_s > 0 and age > tr.next_warn
                    if not (want_abort or want_warn):
                        continue
                    draining = (preempt.draining_ranks()
                                if preempt.pending() else {})
                    behind = []
                    drain_behind = []
                    for r in tr.members:
                        if r == self.rank or r in bye:
                            # a cleanly-exited rank is never blamed
                            # for a stall (false-positive guard for
                            # markers legitimately armed at exit)
                            continue
                        snap = peers.get(r)
                        pseq = 0
                        if snap is not None:
                            pseq = snap.get("sets", {}).get(
                                sid, {}).get("seq", 0)
                        # a stale peer counts as absent even when its
                        # last snapshot showed it caught up: it may
                        # have died mid-collective, after posting
                        if pseq < tr.seq or r in stale or r in suspect:
                            if r in draining:
                                # inside its drain grace window
                                # (core/preempt.py): heading for the
                                # drain commit, not stuck — report,
                                # don't blame.  The exclusion expires
                                # with the window, unlike bye.
                                drain_behind.append(r)
                            elif r in suspect:
                                # partition suspect: silent because it
                                # may be cut off from the KV, not dead
                                # — hold the blame until it recovers
                                # or ages into the dead set (the
                                # transition was logged in _beat_once)
                                pass
                            else:
                                behind.append(r)
                    if not behind:
                        if drain_behind and want_warn:
                            tr.next_warn = age + self.warn_s
                            for r in sorted(drain_behind):
                                drain_notes.append(
                                    (r, draining.get(r, 0.0),
                                     tr.inflight, sid))
                        # everyone (still blamable) dispatched it: a
                        # slow collective, not a stall
                        continue
                    if want_abort:
                        fail = _stall_abort_msg(
                            tr.inflight, sid, tr.seq - 1, age,
                            self.abort_s, behind)
                    elif want_warn:
                        tr.next_warn = age + self.warn_s
                        warns.append(
                            (tr.inflight, sid, tr.seq - 1, age, behind))
            if fail:
                self.failure = fail
                _M_ABORTS.inc()
                if flight.ACTIVE:
                    flight.note("stall_abort", detail=fail[:300])
                flight.dump_postmortem("stall_abort")
        for r, rem, desc, sid in drain_notes:
            logger.info(
                "rank %d draining (%.0fs grace remaining); holding "
                "the heartbeat abort for [%s] (process set %s)",
                r, rem, desc, sid)
        for desc, sid, op, age, behind in warns:
            _M_WARNINGS.inc()
            logger.warning(
                "stalled collective [%s] (process set %s, op #%d): "
                "waited %.1fs; ranks not at the rendezvous: %s",
                desc, sid, op, age, behind,
            )
            if tracing.ACTIVE:
                tracing.instant(
                    "stall_warning", collective=desc, process_set=sid,
                    op_seq=op, waited_s=age,
                    ranks_missing=sorted(behind))
            if flight.ACTIVE:
                flight.note("stall_warning", collective=desc,
                            process_set=sid, op_seq=op,
                            waited_s=round(age, 3),
                            ranks_missing=sorted(behind))


def _make_inspector(st, cfg):
    """Create the configured inspector, or latch ``False`` (disabled)
    when no coordination client exists in this process."""
    try:
        from jax._src import distributed as _jd

        client = _jd.global_state.client
    except Exception:
        client = None
    if client is not None:
        # Transient coordinator blips (or injected kv.* faults) retry
        # with backoff instead of surfacing through the watchdog as an
        # instant failure, and heartbeats carry this incarnation's
        # fencing token — a superseded (zombie) rank's beats are
        # invisible to live readers and the zombie self-fences; see
        # core/retry.py (FencedKV).
        from ..core.retry import fenced_kv

        client = fenced_kv(client, rank=st.rank)
    if client is None:
        st.sync_stall = False
        logger.warning(
            "stall watchdog disabled: no coordination-service client in "
            "this process, so sync collectives cannot be stall-checked "
            "and a diverged rank will hang instead of aborting with a "
            "diagnosis. (Set HVTPU_STALL_CHECK_DISABLE=1 or launch with "
            "--no-stall-check to silence this if intentional.)")
        return None
    mode = str(getattr(cfg, "stall_check_mode", "amortized")).lower()
    if mode not in ("amortized", "strict"):
        raise ValueError(
            f"stall_check_mode (HVTPU_STALL_CHECK_MODE) must be "
            f"'amortized' or 'strict', got {cfg.stall_check_mode!r}")
    if (mode == "strict"
            or getattr(client, "key_value_dir_get", None) is None):
        # amortized detection needs the directory get to read peers'
        # heartbeats in one RPC; without it, fall back to strict
        insp = SyncStallInspector(
            client, st.rank,
            warn_s=cfg.stall_check_time_seconds,
            abort_s=cfg.stall_shutdown_time_seconds,
            generation=st.init_generation,
        )
    else:
        insp = AmortizedStallInspector(
            client, st.rank,
            warn_s=cfg.stall_check_time_seconds,
            abort_s=cfg.stall_shutdown_time_seconds,
            heartbeat_s=getattr(cfg, "stall_heartbeat_seconds", 0.5),
            generation=st.init_generation,
        )
    st.sync_stall = insp
    obs_metrics.register_debug_provider("stall", insp.debug_state)
    return insp


def check(st, ps, desc: str) -> Optional[str]:
    """The eager ops' pre-dispatch hook: record the op (amortized) or
    rendezvous with the other member ranks (strict), or no-op when
    stall checking cannot or should not engage (single member,
    controller thread, disabled, no coordination client).  Returns
    the descriptor when an op was recorded (pass it to ``finish``),
    else None."""
    if ps.size <= 1 or getattr(_tls, "bypass", False):
        return None
    cfg = st.config
    if cfg is None or cfg.stall_check_disable:
        return
    insp = st.sync_stall
    if insp is None:
        insp = _make_inspector(st, cfg)
        if insp is None:
            return
    elif insp is False:
        return
    members = list(ps.ranks) if ps.ranks is not None else list(
        range(st.size))
    if isinstance(insp, AmortizedStallInspector):
        return insp.pre_op(ps.process_set_id, members, desc)
    insp.rendezvous(ps.process_set_id, members, desc)
    return None


# Backend/transport failure markers: a peer that aborted or died
# closes its Gloo/coordination sockets, and the surviving ranks'
# collectives then fail with these rather than hanging.  The reference
# maps such collective failures to HorovodError so elastic recovery
# can catch them — mirror that (HorovodInternalError), and attach the
# watchdog's diagnosis when it lands within a heartbeat.
_TRANSPORT_MARKERS = (
    "Connection closed by peer", "Socket closed", "Connection reset",
    "connection reset", "Broken pipe", "Connection refused",
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "coordination service",
)


def _map_backend_error(insp, err):
    """Re-raise ``err``; transport-shaped failures become
    ``HorovodInternalError`` (recoverable, reference parity), carrying
    the watchdog's diagnosis if one latches within ~a heartbeat."""
    msg = str(err)
    if not any(m in msg for m in _TRANSPORT_MARKERS):
        raise err
    deadline = clock.monotonic() + 2 * getattr(insp, "heartbeat_s", 0.5)
    while insp is not None and clock.monotonic() < deadline:
        if insp.failure:
            raise HorovodInternalError(
                f"{insp.failure} (surfaced via backend error: "
                f"{msg})") from err
        clock.sleep(0.02)
    raise HorovodInternalError(
        f"collective transport failure (a peer likely aborted or "
        f"died): {msg}") from err


def _pending_leaf(out) -> bool:
    """True when any array in ``out`` is still pending — i.e. the call
    returned BEFORE the wire exchange finished, proving asynchronous
    dispatch for its executable."""
    try:
        import jax as _jax

        for leaf in _jax.tree_util.tree_leaves(out):
            ir = getattr(leaf, "is_ready", None)
            if ir is not None and not ir():
                return True
    except Exception:
        pass
    return False


def _execute_once(insp, sid, fn, args, owner, desc):
    """One collective attempt (amortized mode): the wire fault sites
    wrap the real execution, and transport-shaped failures re-raise as
    :class:`wirefault.AttemptFailed` so the retry loop in ``dispatch``
    can run the abort consensus.  Everything else surfaces unchanged.
    The in-flight marker is cleared on EVERY error path (a failed
    attempt must never trip a later healthy op into a false stall
    abort; a re-dispatch re-arms the marker from the ring)."""
    try:
        # Fault site ``wire.send``: the attempt dies BEFORE dispatch
        # put any bytes on the wire — the only failure class eligible
        # to late-join a still-pending attempt.
        if faults.ACTIVE and faults.inject("wire.send"):
            raise wirefault.AttemptFailed(True, ConnectionError(
                "Connection reset: injected wire.send fault"))
        try:
            if getattr(owner, "_hvt_async_proven", False):
                if insp.failure:
                    raise HorovodInternalError(insp.failure)
                out = fn(*args)
            else:
                out, pending = insp.dispatch(sid, fn, args, desc)
                if pending:
                    try:
                        owner._hvt_async_proven = True
                    except Exception:
                        pass
        except (HorovodInternalError, wirefault.AttemptFailed):
            raise
        except Exception as e:
            if any(m in str(e) for m in _TRANSPORT_MARKERS):
                raise wirefault.AttemptFailed(False, e) from e
            raise
        # Fault site ``wire.recv``: the result was torn off the wire
        # after dispatch — mid-flight, so a retry is only granted when
        # consensus proves no member completed the attempt.
        if faults.ACTIVE and faults.inject("wire.recv"):
            raise wirefault.AttemptFailed(False, ConnectionError(
                "Connection reset: injected wire.recv fault"))
        return out
    except BaseException:
        insp._clear_inflight(sid)
        raise


def dispatch(st, ps, fn, args, owner=None, set_id=None, desc=None):
    """The guarded execution hook (amortized mode).

    A COLD executable's first execution can run inline on the
    dispatching thread (observed on the CPU/Gloo backend), which would
    park the main thread uninterruptibly inside a dead collective — so
    cold calls run on the inspector's executor thread.  Once a call
    returns while its result is still pending, the executable has
    PROVEN its dispatch is asynchronous; subsequent calls skip the
    executor (and its thread-handoff cost, a scheduler quantum per op
    on core-contended hosts) because ``wait_ready`` already keeps the
    main thread interruptible.  ``owner`` is the stable callable to
    carry the proof (defaults to ``fn``; pass it when ``fn`` is a
    per-call closure).  Direct call for strict/disabled modes and the
    controller's bypass thread.

    With ``HVTPU_WIRE_RETRIES`` > 0 a transport-shaped attempt failure
    is not immediately job-fatal: the rank votes the attempt dead over
    the fenced KV and reissues it only once the member ranks agree
    nobody holds its result (comm/wirefault.py — RETRY reissues the
    next attempt, LATE_JOIN re-enters the same still-pending attempt,
    ESCALATE falls through to the pre-existing
    ``HorovodInternalError`` → elastic-reset path)."""
    insp = st.sync_stall
    if (not isinstance(insp, AmortizedStallInspector)
            or ps.size <= 1 or getattr(_tls, "bypass", False)):
        return fn(*args)
    owner = owner if owner is not None else fn
    sid = ps.process_set_id if set_id is None else set_id
    budget = wirefault.retry_limit()
    attempt = 0
    fails = 0
    while True:
        try:
            out = _execute_once(insp, sid, fn, args, owner, desc)
            if fails:
                seq, _members, _d = insp.op_info(sid, desc)
                insp.wire_consensus().cleanup(sid, seq, attempt)
            return out
        except wirefault.AttemptFailed as af:
            # late joins consume budget too, or a flapping link could
            # re-enter the same attempt forever
            fails += 1
            if fails > budget or insp.failure:
                _map_backend_error(insp, af.cause)
            seq, members, d = insp.op_info(sid, desc)
            decision = insp.wire_consensus().vote_and_decide(
                sid, seq, attempt, members, d, af.predispatch)
            if decision == wirefault.ESCALATE:
                _map_backend_error(insp, af.cause)
            wirefault.record_retry(insp.rank, sid, seq, attempt,
                                   decision)
            if decision == wirefault.RETRY:
                # every member reissues the NEXT attempt (late joins
                # re-enter the same one) — backoff scales with the
                # attempt so a persistently lossy link drains fast
                attempt += 1
                clock.sleep(wirefault.retry_backoff_s() * attempt)


def finish(st, ps, out, desc: Optional[str] = None):
    """The eager ops' post-dispatch hook (amortized mode only): wait
    for the collective's result interruptibly so a stall or mismatch
    detected by the heartbeat aborts with ``HorovodInternalError``
    instead of parking inside an uninterruptible XLA wait.  ``desc``
    (the value ``check`` returned) names the op for re-arm diagnosis.
    Returns ``out`` unchanged; a no-op for strict/disabled modes
    (strict already rendezvoused pre-dispatch) and for the
    controller's bypass thread."""
    insp = st.sync_stall
    if (not isinstance(insp, AmortizedStallInspector)
            or ps.size <= 1 or getattr(_tls, "bypass", False)):
        return out
    insp.wait_ready(ps.process_set_id, out, desc)
    return out


def stall_guard(fn=None, *, name: Optional[str] = None,
                process_set=None, block: bool = True):
    """Opt-in stall coverage for the JIT/SPMD plane (SURVEY §5.2).

    The collectives INSIDE a user's jitted step cannot be intercepted
    — once every rank has dispatched the step, XLA's schedule is the
    coordination.  What can diverge is the DISPATCH boundary: one
    process stops stepping (crash loop, diverged step count, data
    exhaustion without ``join``) and every other process hangs inside
    an uninterruptible XLA collective with no diagnostic.  Wrapping
    the step function closes exactly that gap with the same machinery
    as the sync eager watchdog:

    - each call records a step mark on a guard-private channel
      (amortized mode: local bookkeeping + the existing heartbeat;
      strict mode: one pre-dispatch KV rendezvous per STEP — cheap at
      step granularity);
    - a rank that stops stepping is diagnosed by name after
      ``stall_check_time_seconds``, and the survivors raise
      ``HorovodInternalError`` after ``stall_shutdown_time_seconds``
      instead of hanging — from the interruptible completion wait
      (``block=True``, default: the wrapper polls the step outputs'
      ``is_ready`` and returns completed arrays) or from the next
      call's pre-dispatch check (``block=False``: keeps JAX's async
      dispatch pipelining; detection then needs the loop to come back
      for another step or to consume an output).
    - two ranks calling DIFFERENTLY-NAMED guarded steps at the same
      point are diagnosed as diverged.

    Usable as a decorator or a wrapper::

        step = hvt.stall_guard(jax.jit(train_step))
        # or
        @hvt.stall_guard(name="train")
        @jax.jit
        def train_step(...): ...

    No-op (plain passthrough) before ``init()``, at world size 1, when
    stall checking is disabled, and on the controller's bypass thread.
    Parity: ``horovod/common/stall_inspector.cc`` (StallInspector) —
    the reference's coordinator sees every op because every op is
    negotiated; here the jit plane negotiates nothing at runtime, so
    coverage is opt-in at the step boundary.
    """
    if fn is None:
        return lambda f: stall_guard(f, name=name,
                                     process_set=process_set,
                                     block=block)
    import functools

    import jax as _jax

    gname = name or getattr(fn, "__name__", None) or "step"

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        from ..core import state as core_state

        st = core_state.global_state()
        if not st.initialized:
            return fn(*args, **kwargs)
        if process_set is None:
            ps = st.process_set_table.global_process_set
        elif isinstance(process_set, int):
            ps = st.process_set_table.get(process_set)
        else:
            ps = process_set
        if ps.size <= 1 or getattr(_tls, "bypass", False):
            return fn(*args, **kwargs)
        cfg = st.config
        if cfg is None or cfg.stall_check_disable:
            return fn(*args, **kwargs)
        insp = st.sync_stall
        if insp is None:
            insp = _make_inspector(st, cfg)
        if insp is None or insp is False:
            return fn(*args, **kwargs)
        # one channel per process set — NOT per guard name: ranks
        # calling differently-named guarded steps at the same point
        # must share a sequence channel so the ring comparison can
        # diagnose them as diverged (the name lives in the descriptor)
        sid = f"jit.{ps.process_set_id}"
        desc = f"jit_step:{gname}"
        members = list(ps.ranks) if ps.ranks is not None else list(
            range(st.size))
        if isinstance(insp, AmortizedStallInspector):
            insp.pre_op(sid, members, desc)
            # the step dispatches via the cold-executor / async-proven
            # machinery: a step whose execution runs inline on the
            # dispatching thread must not wedge the main thread
            call = fn if not kwargs else (
                lambda *a: fn(*a, **kwargs))
            out = dispatch(st, ps, call, args, owner=wrapped,
                           set_id=sid)
            if block:
                for leaf in _jax.tree_util.tree_leaves(out):
                    insp.wait_ready(sid, leaf, desc)
            # block=False: the marker stays armed (async dispatch
            # preserved; a peer that stops stepping is still
            # diagnosed because its counter falls behind, and clean
            # exits are excluded via the goodbye tombstone)
            return out
        insp.rendezvous(sid, members, desc)
        return fn(*args, **kwargs)

    return wrapped


def stop(st) -> None:
    """Shut down the inspector's background thread (called from
    ``core.state.shutdown``)."""
    obs_metrics.unregister_debug_provider("stall")
    insp = st.sync_stall
    if isinstance(insp, AmortizedStallInspector):
        try:
            insp.stop()
        except Exception:
            pass
    st.sync_stall = None
