"""Online anomaly detection: robust per-signal detectors → structured
Incidents that name the offending ranks.

The metrics plane (PR 1), tracing (PR 7), and the overlap profiler
(PR 12) *measure*; nothing *watches*.  This module closes that gap with
detectors cheap enough to run on every step:

- **Rolling median/MAD robust z-score** — per signal, a bounded window
  (``HVTPU_ANOMALY_WINDOW``) whose median is the baseline and whose
  MAD (×1.4826, the Gaussian consistency constant) is the scale; a
  sample scoring ``z ≥ HVTPU_ANOMALY_THRESHOLD`` above the median is
  anomalous.  Median/MAD, unlike mean/σ, don't let the anomaly inflate
  its own yardstick.
- **EWMA baseline** — a smoothed level rides along in every verdict so
  incident records carry "what normal looked like" even while the
  robust window is still absorbing a level shift.
- A **relative floor** (``HVTPU_ANOMALY_MIN_REL``) suppresses firing
  on micro-jitter when the MAD collapses toward zero (perfectly steady
  signals would otherwise make any wiggle infinitely significant).

Signals watched per step (fed by ``metrics.note_step`` from the
stepprof record): step wall time, exposed-comm seconds, data-wait
fraction, KV retry rate.  On rank 0 the controller's arrival-skew
drain feeds per-collective skew plus the last-arriving rank, so
straggler incidents *name the rank* (joining the same counters behind
``hvtpu_collective_last_arriver_total``).

Every fired Incident is counted in ``hvtpu_incidents_total{kind}``,
appended to the flight ring (obs/flight), emitted as a trace instant,
kept in a bounded recent-incidents ring surfaced via the ``anomaly``
``/debug`` provider, and summarized into the fleet health rollup
(fleet/health).

Zero-cost-when-off: seams guard with ``if anomaly.ACTIVE:`` — one
module attribute test when disabled (``HVTPU_ANOMALY=0``), timeit-
enforced in tests.  Time flows through ``core/clock`` so the fabric
simulator runs the *real* detectors on virtual time.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
from typing import Any, Deque, Dict, List, Optional

from ..core import clock as _clock
from . import metrics as _metrics

__all__ = [
    "ACTIVE",
    "AnomalyConfig",
    "RobustDetector",
    "AnomalyEngine",
    "install",
    "uninstall",
    "on_step",
    "on_arrival_skew",
    "get_engine",
    "env_enabled",
    "KINDS",
]

# Incident kinds, one per watched signal.
KINDS = ("step_time", "exposed_comm", "data_wait", "kv_retry",
         "straggler")

# 1.4826 ≈ 1/Φ⁻¹(3/4): scales MAD to σ under normality.
_MAD_SIGMA = 1.4826

_M_INCIDENTS = _metrics.counter(
    "hvtpu_incidents_total",
    "Anomaly incidents raised, labeled by kind (step_time, "
    "exposed_comm, data_wait, kv_retry, straggler).")


def env_enabled() -> bool:
    """``HVTPU_ANOMALY`` gate (default on)."""
    return os.environ.get("HVTPU_ANOMALY", "1").lower() not in (
        "0", "false", "off")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclasses.dataclass
class AnomalyConfig:
    """Detector knobs (env-derived by default; the sim passes explicit
    values for determinism)."""

    window: int = 64        # rolling median/MAD window (samples)
    warmup: int = 16        # no verdicts before this many samples
    threshold: float = 8.0  # robust z-score to fire at
    ewma_alpha: float = 0.15
    min_rel: float = 0.25   # value must exceed baseline by ≥25%
    cooldown_s: float = 30.0  # per-kind refractory period

    @classmethod
    def from_env(cls) -> "AnomalyConfig":
        return cls(
            window=max(8, _env_int("HVTPU_ANOMALY_WINDOW", 64)),
            warmup=max(4, _env_int("HVTPU_ANOMALY_WARMUP", 16)),
            threshold=_env_float("HVTPU_ANOMALY_THRESHOLD", 8.0),
            min_rel=_env_float("HVTPU_ANOMALY_MIN_REL", 0.25),
            cooldown_s=_env_float("HVTPU_ANOMALY_COOLDOWN_S", 30.0),
        )


def _median(sorted_vals: List[float]) -> float:
    n = len(sorted_vals)
    mid = n // 2
    if n % 2:
        return sorted_vals[mid]
    return 0.5 * (sorted_vals[mid - 1] + sorted_vals[mid])


class RobustDetector:
    """One signal's rolling median/MAD z-score + EWMA baseline.

    ``update(value)`` scores the sample against the window *before*
    admitting it (the anomaly must not shift its own baseline), then
    appends.  Returns a verdict dict when anomalous, else None.  A
    sustained level shift therefore fires until roughly half the
    window has absorbed the new level — by design: a regime change IS
    an incident, and the refractory period upstream rate-limits it.
    Only high-side deviations fire; every watched signal is
    "bigger is worse"."""

    def __init__(self, config: AnomalyConfig):
        self.cfg = config
        self._vals: Deque[float] = collections.deque(
            maxlen=config.window)
        self.ewma: Optional[float] = None
        self.samples = 0

    def update(self, value: float) -> Optional[dict]:
        verdict = None
        vals = self._vals
        if len(vals) >= self.cfg.warmup:
            s = sorted(vals)
            med = _median(s)
            mad = _median(sorted(abs(v - med) for v in s))
            # scale floor: MAD of a flat series is 0; fall back to a
            # fraction of the baseline so z stays finite and the
            # relative floor governs.
            scale = max(_MAD_SIGMA * mad,
                        abs(med) * self.cfg.min_rel / self.cfg.threshold,
                        1e-12)
            z = (value - med) / scale
            if (z >= self.cfg.threshold
                    and value > med * (1.0 + self.cfg.min_rel)):
                verdict = {
                    "value": value,
                    "baseline": med,
                    "ewma": self.ewma,
                    "zscore": round(z, 3),
                    "n": len(vals),
                }
        vals.append(value)
        self.samples += 1
        a = self.cfg.ewma_alpha
        self.ewma = (value if self.ewma is None
                     else (1.0 - a) * self.ewma + a * value)
        return verdict


class AnomalyEngine:
    """Per-process detector bank + incident ring.

    ``on_step`` consumes the stepprof step record; ``on_arrival_skew``
    (rank 0) consumes the controller's per-collective skew drain.
    Incidents land in a bounded ring (``/debug`` provider ``anomaly``),
    ``hvtpu_incidents_total{kind}``, the flight ring, and as trace
    instants."""

    def __init__(self, *, rank: int = 0, size: int = 1,
                 config: Optional[AnomalyConfig] = None,
                 incident_window: int = 256):
        self.rank = rank
        self.size = size
        self.cfg = config or AnomalyConfig.from_env()
        self._lock = threading.Lock()
        self._det: Dict[str, RobustDetector] = {  # guarded-by(_lock)
            kind: RobustDetector(self.cfg) for kind in KINDS}
        self._incidents: Deque[dict] = collections.deque(
            maxlen=incident_window)  # hvtpulint: guarded-by(_lock)
        self._counts: Dict[str, int] = {}  # hvtpulint: guarded-by(_lock)
        self._last_fire: Dict[str, float] = {}  # guarded-by(_lock)
        self._kv_retries_prev: Optional[float] = None
        # recent last-arriving ranks: the straggler incident blames the
        # dominant one, mirroring hvtpu_collective_last_arriver_total.
        self._arrivers: Deque[int] = collections.deque(maxlen=64)

    # -- feeds -----------------------------------------------------------
    def on_step(self, rec: dict) -> List[dict]:
        """One stepprof step record: ``{"step_wall_s", "steps",
        "exposed_comm_s", "data_wait_s", ...}``."""
        wall = float(rec.get("step_wall_s") or 0.0)
        if wall <= 0.0:
            return []
        fired = []
        steps = float(rec.get("steps") or 1.0)
        fired += self._check("step_time", wall / max(steps, 1.0))
        fired += self._check(
            "exposed_comm", float(rec.get("exposed_comm_s") or 0.0))
        fired += self._check(
            "data_wait",
            float(rec.get("data_wait_s") or 0.0) / wall)
        cur = _metrics.counter("hvtpu_kv_retries_total").value()
        prev, self._kv_retries_prev = self._kv_retries_prev, cur
        if prev is not None:
            fired += self._check("kv_retry", max(0.0, cur - prev) / wall)
        return fired

    def on_arrival_skew(self, name: str, skew_s: float,
                        last_rank: int) -> List[dict]:
        """One drained arrival-skew sample (rank 0's controller):
        collective ``name`` closed with ``skew_s`` between first and
        last arrival; ``last_rank`` arrived last."""
        with self._lock:
            self._arrivers.append(int(last_rank))
        return self._check("straggler", float(skew_s),
                           tensor=name, last_rank=int(last_rank))

    # -- core ------------------------------------------------------------
    def _check(self, kind: str, value: float, **detail) -> List[dict]:
        with self._lock:
            verdict = self._det[kind].update(value)
            if verdict is None:
                return []
            now = _clock.monotonic()
            last = self._last_fire.get(kind)
            if last is not None and now - last < self.cfg.cooldown_s:
                return []
            self._last_fire[kind] = now
            ranks = self._blame_locked(kind, detail)
            incident = {
                "kind": kind,
                "t_wall": round(_clock.wall(), 6),
                "ranks": ranks,
                **verdict,
            }
            if detail:
                incident["detail"] = detail
            self._incidents.append(incident)
            self._counts[kind] = self._counts.get(kind, 0) + 1
        self._emit(incident)
        return [incident]

    def _blame_locked(self, kind: str, detail: dict) -> List[int]:
        """Name offending ranks.  A straggler incident blames the
        anomalous sample's own last-arriver, plus any rank arriving
        last in a majority of the recent window (a persistent
        straggler even when it isn't last in this one sample — healthy
        jitter never gives any rank a majority).  Process-local
        signals blame this rank."""
        if kind == "straggler":
            blamed = set()
            last = detail.get("last_rank")
            if last is not None:
                blamed.add(int(last))
            n = len(self._arrivers)
            # only trust the majority tally once the window has real
            # depth: a dozen healthy samples can crown a rank by
            # coincidence.
            if n >= (self._arrivers.maxlen or 64) // 2:
                tally: Dict[int, int] = {}
                for r in self._arrivers:
                    tally[r] = tally.get(r, 0) + 1
                blamed.update(
                    r for r, c in tally.items() if c * 2 > n)
            if blamed:
                return sorted(blamed)
        return [self.rank]

    def _emit(self, incident: dict) -> None:
        _M_INCIDENTS.inc(kind=incident["kind"])
        try:
            from . import flight as _flight
            if _flight.ACTIVE:
                _flight.note("incident", **incident)
        except Exception:
            pass
        try:
            from . import tracing as _tracing
            if _tracing.ACTIVE:
                _tracing.instant(
                    "incident", kind=incident["kind"],
                    zscore=incident.get("zscore"),
                    value=incident.get("value"),
                    ranks=incident.get("ranks"))
        except Exception:
            pass

    # -- read side -------------------------------------------------------
    def incidents(self) -> List[dict]:
        with self._lock:
            return list(self._incidents)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "active": True,
                "rank": self.rank,
                "config": dataclasses.asdict(self.cfg),
                "counts": dict(self._counts),
                "ewma": {k: (round(d.ewma, 9)
                             if d.ewma is not None else None)
                         for k, d in self._det.items()},
                "samples": {k: d.samples for k, d in self._det.items()},
                "recent": list(self._incidents)[-16:],
            }


# ---------------------------------------------------------------------------
# module plumbing (ACTIVE flag + None-checked shims, as obs/tracing)
# ---------------------------------------------------------------------------

ACTIVE = False
_engine: Optional[AnomalyEngine] = None
_install_lock = threading.Lock()


def install(*, rank: int = 0, size: int = 1,
            config: Optional[AnomalyConfig] = None
            ) -> Optional[AnomalyEngine]:
    """Create the process engine, flip :data:`ACTIVE`, register the
    ``anomaly`` /debug provider.  No-op when ``HVTPU_ANOMALY=0`` or
    already installed."""
    global ACTIVE, _engine
    if not env_enabled():
        return None
    with _install_lock:
        if _engine is not None:
            return _engine
        eng = AnomalyEngine(rank=rank, size=size, config=config)
        _engine = eng
        ACTIVE = True
    _metrics.register_debug_provider("anomaly", eng.debug_state)
    return eng


def uninstall() -> None:
    global ACTIVE, _engine
    with _install_lock:
        ACTIVE = False
        eng, _engine = _engine, None
    if eng is None:
        return
    try:
        _metrics.unregister_debug_provider("anomaly")
    except Exception:
        pass


def get_engine() -> Optional[AnomalyEngine]:
    return _engine


def on_step(rec: dict) -> None:
    """Feed one step record; callers guard with ``if anomaly.ACTIVE``."""
    e = _engine
    if e is not None:
        e.on_step(rec)


def on_arrival_skew(name: str, skew_s: float, last_rank: int) -> None:
    e = _engine
    if e is not None:
        e.on_arrival_skew(name, skew_s, last_rank)
