"""Exception types for horovod_tpu.

Parity surface: the reference's ``horovod/common/exceptions.py``
(``HorovodInternalError``, ``HostsUpdatedInterrupt``) — the two exception
types the elastic training loop catches to trigger state restore / re-init.
"""


class HorovodTpuError(Exception):
    """Base class for all horovod_tpu errors."""


class HorovodInternalError(HorovodTpuError):
    """A collective operation failed (device loss, comm failure, desync).

    Elastic training loops catch this, roll back to the last committed
    state, re-initialize, and continue (see ``horovod_tpu.elastic``).
    """


class HostsUpdatedInterrupt(HorovodTpuError):
    """The set of participating hosts/slices changed (elastic membership).

    Raised at a commit boundary after the worker-notification service flags
    a membership change; the training loop re-initializes with the new
    world without rolling back state.
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class NotInitializedError(HorovodTpuError):
    """An API requiring ``horovod_tpu.init()`` was called before init."""

    def __init__(self, name: str = "operation"):
        super().__init__(
            f"horovod_tpu has not been initialized; call horovod_tpu.init() "
            f"before using {name}."
        )


class StallError(HorovodTpuError):
    """The stall inspector declared a rank permanently missing."""
