"""Reduction op identifiers, matching the reference's ``ReduceOp`` surface
(horovod/common/common.h and the ``op=`` argument of hvd.allreduce in
horovod/torch/mpi_ops.py: Average, Sum, Adasum, Min, Max, Product).
"""

from __future__ import annotations

import enum


class ReduceOp(enum.IntEnum):
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Module-level aliases matching `hvd.Average` / `hvd.Sum` / `hvd.Adasum`.
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def normalize_op(op, average=None) -> ReduceOp:
    """Resolve the (op, legacy average=) argument pair like the reference
    does in horovod/torch/mpi_ops.py (`handle_average_backwards_compatibility`).
    """
    if average is not None:
        if op is not None:
            raise ValueError("specify either op= or average=, not both")
        return ReduceOp.AVERAGE if average else ReduceOp.SUM
    if op is None:
        return ReduceOp.AVERAGE
    return ReduceOp(op)
