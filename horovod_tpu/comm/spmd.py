"""SPMD collectives: the jit-traceable hot path.

These functions run *inside* ``jax.shard_map`` / ``pjit`` over a mesh
axis.  They are the TPU-native re-expression of the reference's
collective op implementations (horovod/common/ops/nccl_operations.cc
``NCCLAllreduce::Execute`` etc.) — but where the reference dispatches
NCCL calls from a background thread, here the collective is part of the
compiled program: XLA lowers ``psum``/``all_gather``/``ppermute``/
``all_to_all``/``psum_scatter`` to ICI ring/torus transfers, schedules
them asynchronously, and overlaps them with compute.  Program order
replaces the controller (SURVEY.md §7.0): there is no negotiation phase
because every device runs the same program.

Process-set scoping maps to ``axis_index_groups`` (members form one
group, non-members sit in singleton groups), replacing the per-set
communicators of horovod/common/process_set.cc.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .adasum import adasum_reduce
from .compression import Compression, NoneCompressor
from .reduce_ops import ReduceOp, normalize_op


def axis_size(axis_name: str) -> int:
    """Static size of a bound mesh axis at trace time."""
    return lax.axis_size(axis_name)


def rank(axis_name: str):
    """This participant's index along ``axis_name`` (traced value)."""
    return lax.axis_index(axis_name)


def _group_size(axis_name: str, groups) -> int:
    if groups is None:
        return axis_size(axis_name)
    return len(groups[0])


def _is_int8(compression) -> bool:
    from .compression import Int8Compressor

    return (
        compression is Int8Compressor
        or isinstance(compression, Int8Compressor)
        or (isinstance(compression, type) and issubclass(compression, Int8Compressor))
    )


def _is_stochastic_int8(compression) -> bool:
    return _is_int8(compression) and bool(
        getattr(compression, "STOCHASTIC", False)
    )


def _require_equal_groups(groups, op_name: str):
    """XLA requires equal-size replica groups for gather/scatter-shaped
    collectives; ProcessSet.device_groups() can produce unequal groups
    (member group + remainder), which only psum/pmin/pmax accept."""
    if groups is not None and len({len(g) for g in groups}) > 1:
        raise ValueError(
            f"{op_name} requires equal-size axis_index_groups; got sizes "
            f"{[len(g) for g in groups]}. Scope {op_name} to a process set "
            "whose non-members also form equal-size groups, or use the "
            "eager layer (per-set sub-mesh) instead."
        )


def allreduce(
    tensor,
    *,
    axis_name: str,
    op: Optional[ReduceOp] = None,
    average: Optional[bool] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=NoneCompressor,
    groups: Optional[List[List[int]]] = None,
    adasum_segments=None,
):
    """Allreduce inside jit. Parity: EnqueueTensorAllreduce + NCCLAllreduce.

    ``groups`` is an ``axis_index_groups`` partition (from
    ``ProcessSet.device_groups()``) scoping the reduction.
    ``adasum_segments`` — (offset, size) pairs — applies Adasum's dot
    products per-tensor within a fused flat buffer.
    """
    rop = normalize_op(op, average)
    n = _group_size(axis_name, groups)

    if prescale_factor != 1.0:
        tensor = tensor * jnp.asarray(prescale_factor, tensor.dtype)

    if rop == ReduceOp.ADASUM:
        if groups is not None:
            raise NotImplementedError(
                "Adasum over process-set groups is not supported in-jit; "
                "use the global set"
            )
        if _is_int8(compression):
            raise ValueError(
                "int8 compression cannot ride Adasum (per-rank scales "
                "would corrupt the dot products); use fp16/bf16/none"
            )
        wire, ctx = compression.compress(tensor)
        out = adasum_reduce(wire, axis_name, n, adasum_segments)
        out = compression.decompress(out, ctx)
    elif rop in (ReduceOp.SUM, ReduceOp.AVERAGE):
        if _is_int8(compression) and jnp.issubdtype(
            tensor.dtype, jnp.floating
        ):
            # int8 codes cannot ride psum (per-rank scales, overflow);
            # route to the EQuARX-style two-phase quantized allreduce.
            if groups is not None:
                raise NotImplementedError(
                    "int8 compression over process-set groups is not "
                    "supported; use the global set"
                )
            from .quantized import quantized_allreduce

            out = quantized_allreduce(
                tensor, axis_name=axis_name,
                average=(rop == ReduceOp.AVERAGE),
                stochastic=_is_stochastic_int8(compression),
            ).astype(tensor.dtype)
        else:
            wire, ctx = compression.compress(tensor)
            out = lax.psum(wire, axis_name, axis_index_groups=groups)
            out = compression.decompress(out, ctx)
            if rop == ReduceOp.AVERAGE:
                if jnp.issubdtype(out.dtype, jnp.integer):
                    out = out // n
                else:
                    out = out / n
    elif rop == ReduceOp.MIN:
        out = lax.pmin(tensor, axis_name, axis_index_groups=groups)
    elif rop == ReduceOp.MAX:
        out = lax.pmax(tensor, axis_name, axis_index_groups=groups)
    elif rop == ReduceOp.PRODUCT:
        _require_equal_groups(groups, "allreduce(op=Product)")
        gathered = lax.all_gather(tensor, axis_name, axis_index_groups=groups)
        out = jnp.prod(gathered, axis=0)
    else:
        raise ValueError(f"unsupported op {rop}")

    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, out.dtype)
    return out


def grouped_allreduce(
    tensors: Sequence,
    *,
    axis_name: str,
    op: Optional[ReduceOp] = None,
    average: Optional[bool] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=NoneCompressor,
    groups: Optional[List[List[int]]] = None,
):
    """One fused collective for a list of tensors.

    Parity: hvd.grouped_allreduce / horovod/common/group_table.cc — the
    group is always executed as a unit.  Here that means: flatten all
    members into one flat buffer (one wire cast, one psum) and unpack,
    exactly what FusionBufferManager does for a fused Response.
    Falls back to per-tensor ops for reductions that don't fuse (min/max/
    product/adasum keep per-tensor semantics).
    """
    rop = normalize_op(op, average)
    tensors = list(tensors)
    if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE) or not tensors:
        return [
            allreduce(
                t,
                axis_name=axis_name,
                op=rop,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                compression=compression,
                groups=groups,
            )
            for t in tensors
        ]

    from .packing import pack_flat, unpack_flat

    flat, specs = pack_flat(tensors)
    red = allreduce(
        flat,
        axis_name=axis_name,
        op=rop,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
        compression=compression,
        groups=groups,
    )
    return unpack_flat(red, specs)


def allgather(
    tensor,
    *,
    axis_name: str,
    groups: Optional[List[List[int]]] = None,
):
    """Concatenate per-participant tensors along dim 0.

    Parity: EnqueueTensorAllgather / NCCLAllgather.  The reference
    negotiates per-rank first-dim sizes; in SPMD every participant has
    the same shape by construction (the dynamic-shape path lives in the
    eager layer, horovod_tpu.comm.eager).
    """
    _require_equal_groups(groups, "allgather")
    return lax.all_gather(
        tensor, axis_name, axis_index_groups=groups, tiled=True
    )


def broadcast(
    tensor,
    *,
    root_rank: int,
    axis_name: str,
    groups: Optional[List[List[int]]] = None,
):
    """Every participant gets root's value.

    Implemented as select + psum: contribute zeros unless we are root.
    XLA lowers this to a broadcast-from-root on ICI (and folds the
    select); avoids all_gather's N× memory.
    """
    idx = lax.axis_index(axis_name)
    contrib = jnp.where(idx == root_rank, tensor, jnp.zeros_like(tensor))
    if jnp.issubdtype(tensor.dtype, jnp.bool_):
        return lax.psum(
            contrib.astype(jnp.int8), axis_name, axis_index_groups=groups
        ).astype(jnp.bool_)
    return lax.psum(contrib, axis_name, axis_index_groups=groups)


def alltoall(
    tensor,
    *,
    axis_name: str,
    groups: Optional[List[List[int]]] = None,
):
    """Equal-split all-to-all along dim 0 (Ulysses building block).

    Parity: EnqueueTensorAlltoall.  dim0 must be divisible by the group
    size; the variable-``splits`` form is provided by the eager layer.
    """
    _require_equal_groups(groups, "alltoall")
    n = _group_size(axis_name, groups)
    if tensor.shape[0] % n:
        raise ValueError(
            f"alltoall dim0 {tensor.shape[0]} not divisible by group size {n}"
        )
    return lax.all_to_all(
        tensor,
        axis_name,
        split_axis=0,
        concat_axis=0,
        axis_index_groups=groups,
        tiled=True,
    )


def reducescatter(
    tensor,
    *,
    axis_name: str,
    op: Optional[ReduceOp] = None,
    groups: Optional[List[List[int]]] = None,
):
    """Reduce then scatter along dim 0 (ZeRO building block).

    Parity: EnqueueTensorReducescatter.  dim0 must be divisible by the
    group size.
    """
    rop = normalize_op(op, None)
    if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("reducescatter supports Sum and Average")
    _require_equal_groups(groups, "reducescatter")
    n = _group_size(axis_name, groups)
    if tensor.shape[0] % n:
        raise ValueError(
            f"reducescatter dim0 {tensor.shape[0]} not divisible by {n}"
        )
    out = lax.psum_scatter(
        tensor, axis_name, axis_index_groups=groups, tiled=True
    )
    if rop == ReduceOp.AVERAGE:
        if jnp.issubdtype(out.dtype, jnp.integer):
            out = out // n
        else:
            out = out / n
    return out


def barrier(axis_name: str):
    """Synchronize all participants (parity: hvd.barrier)."""
    return lax.psum(jnp.zeros((), jnp.int32), axis_name)
