"""Worker entry for the programmatic ``horovod_tpu.runner.run()`` API.

Parity surface: ``horovod/runner/__init__.py`` (``run``) +
``horovod/runner/task_fn.py`` — the launcher pickles the user function,
each rank unpickles and calls it, and per-rank return values are
pickled back for the launcher to collect.
"""

from __future__ import annotations

import os
import pickle
import sys
import traceback

from . import secret


def _load(path: str):
    """Verify the HMAC before a single byte is unpickled (parity:
    secret.py-signed service messages — unverified pickle is code
    execution)."""
    with open(path, "rb") as f:
        signed = f.read()
    blob = secret.verify(secret.require_env_key(), signed)
    try:
        import cloudpickle

        return cloudpickle.loads(blob)
    except ImportError:
        return pickle.loads(blob)


def main(fn_path: str, out_dir: str) -> int:
    rank = int(os.environ.get("HVTPU_RANK", "0"))
    result_path = os.path.join(out_dir, f"rank_{rank}.pkl")
    try:
        fn, args, kwargs = _load(fn_path)
        result = fn(*args, **kwargs)
        payload = (True, result)
        code = 0
    except BaseException:
        payload = (False, traceback.format_exc())
        code = 1
    tmp = result_path + ".tmp"
    blob = pickle.dumps(payload)
    try:
        signed = secret.sign(secret.require_env_key(), blob)
    except secret.SignatureError:
        # no key (e.g. run_task invoked by hand): ship the failure
        # traceback unsigned — the launcher only accepts this when it
        # also has no key
        signed = blob
    with open(tmp, "wb") as f:
        f.write(signed)
    os.replace(tmp, result_path)
    try:
        from ..comm.stall import poisoned

        if poisoned():
            # The stall watchdog abandoned a pending collective: the
            # XLA runtime holds a thread parked inside it, so normal
            # interpreter teardown would hang at the client
            # destructor.  Run the orderly shutdown first (its
            # coordination barrier lets still-healthy peers finish
            # before the leader goes away), then hard-exit past the
            # destructor with the honest status — the result file is
            # already durably delivered.
            from ..core import state as _core_state

            try:
                _core_state.shutdown()
            except Exception:
                pass
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(code)
    except ImportError:
        pass
    return code


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], sys.argv[2]))
