"""Traffic-driven autoscaling hooks for fleet jobs.

A serving-shaped job (ROADMAP: "millions of users") wants its world
size to track load: grow toward ``max_np`` when a queue-depth or
request-rate signal runs hot, shrink toward ``min_np`` when it runs
cold.  The mechanism reuses what already exists:

- **Signal intake** rides the notice-file idiom: a
  :class:`FileSignal` polls a small file (written by a load balancer,
  a queue exporter, a test) holding one number.  No new transport.
- **Shrink** goes through the SAME planned-drain channel priority
  preemption uses (per-rank ``core/preempt.py`` notice files): a
  scale-down is a planned resize — zero lost steps, no restart-budget
  or blacklist strike.
- **Grow** widens the job's allocation; the elastic driver's own
  discovery poll notices and resets the world at the next commit
  boundary (the existing scale-up semantics, including its budget
  accounting).
- **Time** flows exclusively through the ``core/clock.py`` seam:
  the arbiter passes ``clock.monotonic()`` into :meth:`evaluate`, so
  the fabric simulator drives debounce windows on virtual time and
  tier-1 tests use a fake clock with no real sleeps.

Debounce: the signal must stay beyond a watermark CONTINUOUSLY for
``debounce_s`` before an action fires, and after any action the scaler
holds fire for ``cooldown_s`` — a noisy signal crossing the watermark
once per poll can never thrash the world size.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Tuple

__all__ = ["Autoscaler", "FileSignal"]


def _default_debounce_s() -> float:
    return float(
        os.environ.get("HVTPU_FLEET_AUTOSCALE_DEBOUNCE_SECONDS", "10")
        or 10)


class FileSignal:
    """Queue-depth / request-rate intake over the notice-file channel:
    the file holds one number; absent or unparseable reads as "no
    signal" (None), which resets the debounce timers rather than
    triggering anything."""

    def __init__(self, path: str):
        self.path = path

    def __call__(self) -> Optional[float]:
        try:
            with open(self.path) as f:
                return float(f.read().strip())
        except (OSError, ValueError):
            return None

    def __repr__(self) -> str:
        return f"FileSignal({self.path!r})"


class Autoscaler:
    """Debounced two-watermark policy over one scalar signal.

    ``evaluate(now)`` returns ``("grow", step)``, ``("shrink", step)``
    or None; the arbiter applies the decision against the job's
    min/max and the pool's free capacity.  Pure logic over caller-
    provided ``now`` — no threads, no sleeps, no host clock."""

    def __init__(self, signal_fn: Callable[[], Optional[float]], *,
                 high: float, low: float, step: int = 1,
                 debounce_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None):
        if low >= high:
            raise ValueError(
                f"autoscale watermarks inverted: low={low} >= "
                f"high={high}")
        self.signal_fn = signal_fn
        self.high = float(high)
        self.low = float(low)
        self.step = max(1, int(step))
        self.debounce_s = (_default_debounce_s()
                           if debounce_s is None else float(debounce_s))
        self.cooldown_s = (self.debounce_s if cooldown_s is None
                           else float(cooldown_s))
        self.last_signal: Optional[float] = None
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._last_action_t: Optional[float] = None

    @classmethod
    def from_spec(cls, spec: dict) -> Optional["Autoscaler"]:
        """Build from a JobSpec's validated ``autoscale`` block; the
        signal file falls back to ``HVTPU_FLEET_AUTOSCALE_SIGNAL_FILE``.
        Returns None when no signal source is configured anywhere."""
        path = spec.get("signal_file") or os.environ.get(
            "HVTPU_FLEET_AUTOSCALE_SIGNAL_FILE")
        if not path:
            return None
        return cls(FileSignal(path),
                   high=float(spec["high"]), low=float(spec["low"]),
                   step=int(spec.get("step", 1)),
                   debounce_s=spec.get("debounce_s"),
                   cooldown_s=spec.get("cooldown_s"))

    def evaluate(self, now: float) -> Optional[Tuple[str, int]]:
        """One arbiter-tick evaluation at virtual-or-real time ``now``."""
        value = self.signal_fn()
        self.last_signal = value
        if value is None:
            # no signal ≠ low load: reset, never act on absence
            self._above_since = None
            self._below_since = None
            return None
        if (self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s):
            return None
        if value > self.high:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since >= self.debounce_s:
                self._above_since = None
                self._last_action_t = now
                return ("grow", self.step)
        elif value < self.low:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since >= self.debounce_s:
                self._below_since = None
                self._last_action_t = now
                return ("shrink", self.step)
        else:
            self._above_since = None
            self._below_since = None
        return None

    def debug_state(self) -> dict:
        return {
            "signal": self.last_signal,
            "high": self.high, "low": self.low, "step": self.step,
            "debounce_s": self.debounce_s,
            "cooldown_s": self.cooldown_s,
        }
