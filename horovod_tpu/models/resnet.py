"""ResNet v1.5 family in flax.linen, bf16-first for the TPU MXU.

This is the flagship benchmark model: the reference's headline numbers
are ResNet-50/101 synthetic-ImageNet scaling
(examples/pytorch/pytorch_synthetic_benchmark.py,
examples/tensorflow2/tensorflow2_synthetic_benchmark.py; README.rst
Benchmarks — see BASELINE.md), so parity is measured on the same
architecture.

TPU notes: compute in bfloat16 (MXU-native), params/BN stats in fp32;
NHWC layout (XLA:TPU's preferred conv layout); BatchNorm supports
cross-replica sync via ``axis_name`` — the analog of the reference's
horovod/torch/sync_batch_norm.py (allgather-based SyncBatchNorm).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from .tpu_norm import TpuBatchNorm

ModuleDef = Any


def _space_to_depth(x, block: int = 2):
    """NHWC space-to-depth: (N, H, W, C) -> (N, H/b, W/b, C*b*b).

    Used by the ``stem="s2d"`` path: the 7x7/2 stem conv on a 3-channel
    input runs at ~2% MXU utilization (3 channels padded to the 128-wide
    lane dim).  Reformatting 2x2 spatial blocks into 12 channels and
    convolving 4x4/1 computes the same downsampling stem with a 4x
    deeper reduction dim — the standard MLPerf ResNet TPU optimization.
    """
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, c * block * block)


class BottleneckBlock(nn.Module):
    """ResNet v1.5 bottleneck (stride on the 3x3, as in torchvision)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = checkpoint_name(y, "conv_out")
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = checkpoint_name(y, "conv_out")
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = checkpoint_name(y, "conv_out")
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = checkpoint_name(residual, "conv_out")
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = checkpoint_name(y, "conv_out")
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = checkpoint_name(y, "conv_out")
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = checkpoint_name(residual, "conv_out")
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5.

    ``bn_axis_name``: mesh axis for cross-replica (synchronized)
    BatchNorm — pass the data-parallel axis to get SyncBatchNorm
    semantics; None keeps per-replica stats like ordinary DP training.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = None
    # "conv7" = classic 7x7/2 stem; "s2d" = mathematically-equivalent-
    # shape space-to-depth stem (2x2 blocks -> 12 channels, 4x4/1 conv):
    # the 3-channel 7x7 conv wastes the 128-lane MXU reduction dim.
    stem: str = "conv7"
    # Selective rematerialization: store ONLY conv outputs for the
    # backward pass and recompute the BN/ReLU elementwise chain from
    # them.  Without it both the pre-BN conv output AND the post-ReLU
    # activation are live fwd->bwd; dropping the latter removes ~1/3 of
    # the step's HBM traffic on an HBM-bandwidth-bound model for zero
    # extra conv FLOPs (elementwise recompute only).
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = partial(
            TpuBatchNorm,  # flax-BatchNorm semantics, TPU-fast stats
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.bn_axis_name if train else None,
        )
        act = nn.relu

        block_cls = self.block_cls
        if self.remat:
            block_cls = nn.remat(
                block_cls,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "conv_out"
                ),
                # under the lax.scan train loop CSE cannot undo the
                # remat, and the no-opt-barrier form schedules better
                prevent_cse=False,
            )

        x = x.astype(self.dtype)
        if self.stem == "s2d":
            x = _space_to_depth(x, 2)
            x = conv(self.num_filters, (4, 4), (1, 1), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=act,
                )(x)
        # flattened (N, H*W, C) mean: XLA:TPU's multi-axis spatial
        # reduce is slow (same issue TpuBatchNorm works around)
        n, h, w, c = x.shape
        x = jnp.mean(x.reshape(n, h * w, c), axis=1)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
