"""Elastic training script used by the fault-injection integration
tests (the analog of the reference's test/integration/elastic_common.py
worker script).  Trains EPOCHS epochs with a commit per epoch; can
crash a given rank once at a given epoch (marker-file gated).
"""

import os
import sys
import time

import horovod_tpu as hvt
import horovod_tpu.elastic as elastic


def main():
    hvt.init()
    epochs = int(os.environ.get("ELASTIC_EPOCHS", "6"))
    sleep_s = float(os.environ.get("EPOCH_SLEEP", "0.3"))
    state = elastic.ObjectState(epoch=0, total=0.0)
    # Relaunched-incarnation contract: the run decorator fires reset
    # callbacks (after sync) when HVTPU_ELASTIC_GENERATION > 0, so
    # world-size-derived values can be rebuilt — the integration
    # tests assert this line appears with the POST-resize size.
    state.register_reset_callbacks([
        lambda: print(
            f"RESET_CB rank={hvt.rank()} size={hvt.size()} "
            f"gen={os.environ.get('HVTPU_ELASTIC_GENERATION')}",
            flush=True,
        )
    ])

    @elastic.run
    def train(state):
        import jax.numpy as jnp

        while state.epoch < epochs:
            out = hvt.allreduce(jnp.ones(4), op=hvt.Sum)
            state.total += float(out[0])
            if hvt.rank() == 0:
                print(
                    f"EPOCH epoch={state.epoch} size={hvt.size()} "
                    f"total={state.total}",
                    flush=True,
                )
            # Crash injection: rank CRASH_RANK dies once it reaches
            # CRASH_EPOCH, up to CRASH_COUNT times total (the marker
            # file carries the count across incarnations) — repeated
            # crashes on one host drive the driver's blacklist policy.
            crash_marker = os.environ.get("CRASH_MARKER")
            if (
                crash_marker
                and hvt.rank() == int(os.environ.get("CRASH_RANK", "1"))
                and state.epoch >= int(os.environ.get("CRASH_EPOCH", "2"))
            ):
                n = 0
                if os.path.exists(crash_marker):
                    n = int(open(crash_marker).read().strip() or 0)
                if n < int(os.environ.get("CRASH_COUNT", "1")):
                    with open(crash_marker, "w") as f:
                        f.write(str(n + 1))
                    print(f"CRASHING rank={hvt.rank()} (strike {n + 1})",
                          file=sys.stderr, flush=True)
                    os._exit(1)
            state.epoch += 1
            time.sleep(sleep_s)
            state.commit()
        if hvt.rank() == 0:
            print(f"DONE size={hvt.size()} epoch={state.epoch}",
                  flush=True)

    train(state)


if __name__ == "__main__":
    main()
