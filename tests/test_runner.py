"""Launcher unit tests — command-line/env capture with no real spawn
(reference pattern: test/single/test_run.py asserting constructed
cmdlines and env handling via mocks; SURVEY.md §4 pattern 3).
"""

import argparse

import pytest

from horovod_tpu.runner import hosts as hosts_mod
from horovod_tpu.runner import launch as launch_mod


class TestHostParsing:
    def test_parse_simple(self):
        hs = hosts_mod.parse_host_spec("h1:2,h2:4")
        assert [(h.hostname, h.slots) for h in hs] == [("h1", 2), ("h2", 4)]

    def test_parse_default_slot(self):
        hs = hosts_mod.parse_host_spec("h1,h2:3")
        assert [(h.hostname, h.slots) for h in hs] == [("h1", 1), ("h2", 3)]

    def test_parse_rejects_bad_slots(self):
        with pytest.raises(ValueError):
            hosts_mod.parse_host_spec("h1:0")
        with pytest.raises(ValueError):
            hosts_mod.parse_host_spec("")

    def test_assignments_host_major(self):
        slots = hosts_mod.get_host_assignments(
            hosts_mod.parse_host_spec("a:2,b:2"), 4
        )
        assert [(s.hostname, s.rank, s.local_rank) for s in slots] == [
            ("a", 0, 0), ("a", 1, 1), ("b", 2, 0), ("b", 3, 1)
        ]
        # cross communicator: ranks with the same local_rank across hosts
        assert [(s.cross_rank, s.cross_size) for s in slots] == [
            (0, 2), (0, 2), (1, 2), (1, 2)
        ]
        assert all(s.local_size == 2 and s.size == 4 for s in slots)

    def test_assignments_partial_fill(self):
        slots = hosts_mod.get_host_assignments(
            hosts_mod.parse_host_spec("a:4,b:4"), 5
        )
        assert [s.hostname for s in slots] == ["a"] * 4 + ["b"]
        assert slots[4].local_rank == 0 and slots[4].local_size == 1
        # local_rank 0 exists on both hosts -> cross_size 2 for those
        assert slots[0].cross_size == 2 and slots[4].cross_size == 2
        assert slots[1].cross_size == 1  # local_rank 1 only on host a

    def test_oversubscription_rejected(self):
        with pytest.raises(ValueError, match="exceeds available slots"):
            hosts_mod.get_host_assignments(
                hosts_mod.parse_host_spec("a:2"), 3
            )


class TestParseArgs:
    def test_minimal(self):
        args = launch_mod.parse_args(["-np", "2", "python", "train.py"])
        assert args.np == 2
        assert args.command == ["python", "train.py"]

    def test_flag_mirroring_and_separator(self):
        args = launch_mod.parse_args(
            ["-np", "4", "--fusion-threshold-mb", "32",
             "--cycle-time-ms", "2.5", "--timeline-filename", "/tmp/t.json",
             "--autotune", "--compression", "fp16", "--cpu-devices", "1",
             "--", "python", "-m", "mymod"]
        )
        assert args.command == ["python", "-m", "mymod"]
        assert args.fusion_threshold_mb == 32.0
        assert args.autotune and args.compression == "fp16"

    def test_np_required_without_discovery(self):
        with pytest.raises(SystemExit):
            launch_mod.parse_args(["python", "x.py"])

    def test_command_required(self):
        with pytest.raises(SystemExit):
            launch_mod.parse_args(["-np", "2"])


class TestWorkerEnv:
    def _slot(self, rank=1):
        return hosts_mod.SlotInfo(
            hostname="localhost", rank=rank, size=4,
            local_rank=rank, local_size=4, cross_rank=0, cross_size=1,
        )

    def test_env_block(self):
        env = launch_mod.build_worker_env(
            {"PATH": "/bin"}, self._slot(), "10.0.0.1", 9999
        )
        assert env["HVTPU_RANK"] == "1"
        assert env["HVTPU_SIZE"] == "4"
        assert env["HVTPU_LOCAL_RANK"] == "1"
        assert env["HVTPU_COORDINATOR_ADDR"] == "10.0.0.1"
        assert env["HVTPU_COORDINATOR_PORT"] == "9999"
        assert env["PATH"] == "/bin"  # base env preserved

    def test_flags_mirrored_to_env(self):
        args = launch_mod.parse_args(
            ["-np", "4", "--fusion-threshold-mb", "32",
             "--cycle-time-ms", "2.5", "--autotune",
             "--stall-check-time", "5", "--log-level", "debug",
             "--cpu-devices", "2", "python", "x.py"]
        )
        env = launch_mod.build_worker_env({}, self._slot(), "h", 1, args)
        assert env["HVTPU_FUSION_THRESHOLD_MB"] == "32.0"
        assert env["HVTPU_CYCLE_TIME"] == "2.5"
        assert env["HVTPU_AUTOTUNE"] == "1"
        assert env["HVTPU_STALL_CHECK_TIME_SECONDS"] == "5.0"
        assert env["HVTPU_LOG_LEVEL"] == "debug"
        assert env["HVTPU_CPU_DEVICES"] == "2"
        # unset flags must not leak empty env vars
        assert "HVTPU_TIMELINE" not in env
        assert "HVTPU_COMPRESSION" not in env

    def test_integrity_flags_mirrored_to_env(self):
        """--audit-every / --audit-action / --nonfinite-action reach
        workers as HVTPU_AUDIT_EVERY / HVTPU_AUDIT_ACTION /
        HVTPU_NONFINITE_ACTION (docs/robustness.md Integrity)."""
        args = launch_mod.parse_args(
            ["-np", "2", "--audit-every", "16", "--audit-action",
             "warn", "--nonfinite-action", "zero", "python", "x.py"]
        )
        env = launch_mod.build_worker_env({}, self._slot(), "h", 1, args)
        assert env["HVTPU_AUDIT_EVERY"] == "16"
        assert env["HVTPU_AUDIT_ACTION"] == "warn"
        assert env["HVTPU_NONFINITE_ACTION"] == "zero"
        # unset integrity flags must not leak
        args = launch_mod.parse_args(["-np", "2", "python", "x.py"])
        env = launch_mod.build_worker_env({}, self._slot(), "h", 1, args)
        assert "HVTPU_AUDIT_EVERY" not in env
        assert "HVTPU_NONFINITE_ACTION" not in env
        # bad values are rejected at the CLI, not deep in a worker
        with pytest.raises(SystemExit):
            launch_mod.parse_args(["-np", "2", "--nonfinite-action",
                                   "explode", "python", "x.py"])


class TestSshCommand:
    def test_ssh_cmdline(self):
        cmd = launch_mod.build_ssh_command(
            "worker-3",
            ["python", "train.py", "--lr", "0.1"],
            {"HVTPU_RANK": "3", "HVTPU_SIZE": "8", "PATH": "/bin",
             "JAX_PLATFORMS": "tpu", "SECRET": "x"},
            cwd="/job",
        )
        assert cmd[0] == "ssh"
        assert "worker-3" in cmd
        remote = cmd[-1]
        # only the framework namespace is forwarded
        assert "HVTPU_RANK=3" in remote and "HVTPU_SIZE=8" in remote
        assert "JAX_PLATFORMS=tpu" in remote
        assert "SECRET" not in remote and "PATH=/bin" not in remote
        assert remote.startswith("cd /job && env ")
        assert remote.endswith("python train.py --lr 0.1")


class TestFreePort:
    def test_find_free_port(self):
        p1 = launch_mod.find_free_port()
        assert 1024 < p1 < 65536


class TestNicDiscovery:
    """NIC probing (parity: driver_service.py interface discovery)."""

    def test_local_interfaces_include_loopback(self):
        from horovod_tpu.runner import nic

        ifaces = nic.local_interfaces()
        assert any(a.startswith("127.") for _, a in ifaces)

    def test_probe_returns_non_loopback(self):
        from horovod_tpu.runner import nic

        if not [a for _, a in nic.local_interfaces(usable_only=True)
                if not a.startswith("127.")]:
            pytest.skip("loopback-only host: the probe correctly raises "
                        "here; nothing to assert about selection")
        addr = nic.probe_coordinator_addr()
        assert not addr.startswith("127.")
        assert addr in {a for _, a in nic.local_interfaces()}

    def test_resolve_interface_name_and_literal(self):
        from horovod_tpu.runner import nic

        ifaces = nic.local_interfaces()
        name, addr = ifaces[0]
        assert nic.resolve_interface(name) == addr
        # literal addresses pass through untouched
        assert nic.resolve_interface("10.1.2.3") == "10.1.2.3"

    def test_resolve_interface_typo_raises(self):
        # a typo'd interface name must error immediately, not become a
        # bogus coordinator address and a silent rendezvous hang
        from horovod_tpu.runner import nic

        with pytest.raises(ValueError, match="neither a local interface"):
            nic.resolve_interface("eth00-definitely-not-real")

    def test_mixed_spec_uses_probe(self, monkeypatch):
        from horovod_tpu.runner import launch, nic
        from horovod_tpu.runner.hosts import get_host_assignments, \
            parse_host_spec

        seen = {}

        def fake_probe(remote_host=None):
            seen["remote_host"] = remote_host
            return "10.9.8.7"

        monkeypatch.setattr(nic, "probe_coordinator_addr", fake_probe)
        slots = get_host_assignments(
            parse_host_spec("localhost:1,remote1:1"), 2)
        assert launch._default_coordinator_addr(slots) == "10.9.8.7"
        # the probe must aim at an actual remote worker host so the
        # route lookup reflects the fabric the job will really use
        assert seen["remote_host"] == "remote1"

    def test_probe_prefers_egress_over_enumeration_order(self, monkeypatch):
        # a docker bridge (172.17.0.1: global scope, iface UP) sorting
        # first must NOT win over the interface carrying the route
        from horovod_tpu.runner import nic

        monkeypatch.setattr(
            nic, "local_interfaces",
            lambda usable_only=False: [("docker0", "172.17.0.1"),
                                       ("eth0", "10.0.0.5")])
        monkeypatch.setattr(nic, "_egress_addr", lambda target: "10.0.0.5")
        assert nic.probe_coordinator_addr("remote1") == "10.0.0.5"

    def test_probe_falls_back_when_no_route(self, monkeypatch):
        from horovod_tpu.runner import nic

        monkeypatch.setattr(
            nic, "local_interfaces",
            lambda usable_only=False: [("eth0", "10.0.0.5")])
        monkeypatch.setattr(nic, "_egress_addr", lambda target: None)
        assert nic.probe_coordinator_addr() == "10.0.0.5"

    def test_all_local_stays_loopback(self):
        from horovod_tpu.runner import launch
        from horovod_tpu.runner.hosts import get_host_assignments, \
            parse_host_spec

        slots = get_host_assignments(
            parse_host_spec("localhost:2"), 2)
        assert launch._default_coordinator_addr(slots) == "127.0.0.1"

    def test_ssh_command_override(self, monkeypatch):
        from horovod_tpu.runner import launch

        monkeypatch.setenv("HVTPU_SSH_COMMAND", "python /x/fake_ssh.py")
        cmd = launch.build_ssh_command(
            "h1", ["python", "train.py"], {"HVTPU_RANK": "1"})
        assert cmd[:2] == ["python", "/x/fake_ssh.py"]
        assert cmd[2] == "h1"
        assert "HVTPU_RANK=1" in cmd[3]

    def test_ssh_port_and_identity_flags(self, monkeypatch):
        from horovod_tpu.runner import launch

        monkeypatch.delenv("HVTPU_SSH_COMMAND", raising=False)
        cmd = launch.build_ssh_command(
            "h1", ["python", "t.py"], {"HVTPU_RANK": "0"},
            ssh_port=2222, ssh_identity_file="/k/id_ed25519")
        ssh_prefix = cmd[:cmd.index("h1")]
        assert "-p" in ssh_prefix and "2222" in ssh_prefix
        assert "-i" in ssh_prefix and "/k/id_ed25519" in ssh_prefix

    def test_env_passthrough_crosses_ssh(self, monkeypatch):
        # -x vars must survive the ssh export filter (they are outside
        # the HVTPU_/JAX_ namespace, which is the filter's default)
        from horovod_tpu.runner import launch

        monkeypatch.delenv("HVTPU_SSH_COMMAND", raising=False)
        env = {"HVTPU_RANK": "0", "MY_APP_FLAG": "on", "OTHER": "x"}
        cmd = launch.build_ssh_command(
            "h1", ["python", "t.py"], env,
            extra_env_keys=["MY_APP_FLAG"])
        inner = cmd[-1]
        assert "MY_APP_FLAG=on" in inner
        assert "OTHER" not in inner


class TestFlagPlumbing:
    """New round-4 flags → worker env (parity: horovodrun parse_args)."""

    def _env_for(self, argv):
        from horovod_tpu.runner import launch
        from horovod_tpu.runner.hosts import get_host_assignments, \
            parse_host_spec

        args = launch.parse_args(argv + ["--", "python", "x.py"])
        slots = get_host_assignments(parse_host_spec("localhost:2"), 2)
        return launch.build_worker_env(
            {"INHERITED": "yes"}, slots[0], "127.0.0.1", 1234, args)

    def test_disable_cache_maps_to_capacity_zero(self):
        env = self._env_for(["-np", "2", "--disable-cache"])
        assert env["HVTPU_CACHE_CAPACITY"] == "0"

    def test_no_stall_check_maps_to_disable(self):
        env = self._env_for(["-np", "2", "--no-stall-check"])
        assert env["HVTPU_STALL_CHECK_DISABLE"] == "1"

    def test_hierarchical_allreduce_flag(self):
        env = self._env_for(["-np", "2", "--hierarchical-allreduce"])
        assert env["HVTPU_HIERARCHICAL_ALLREDUCE"] == "1"

    def test_metrics_port_flag(self):
        env = self._env_for(["-np", "2", "--metrics-port", "9090"])
        assert env["HVTPU_METRICS_PORT"] == "9090"
        # unset: never exported, endpoint stays off
        assert "HVTPU_METRICS_PORT" not in self._env_for(["-np", "2"])

    def test_trace_dir_flag(self):
        env = self._env_for(["-np", "2", "--trace-dir", "/tmp/tr"])
        assert env["HVTPU_TRACE"] == "/tmp/tr"
        # unset: never exported, tracing stays off on the workers
        assert "HVTPU_TRACE" not in self._env_for(["-np", "2"])

    def test_flight_flags(self):
        env = self._env_for(["-np", "2", "--flight-dir", "/tmp/fl",
                             "--flight-window", "512"])
        assert env["HVTPU_FLIGHT_DIR"] == "/tmp/fl"
        assert env["HVTPU_FLIGHT_WINDOW"] == "512"
        # unset: never exported — the recorder falls back to its env
        # defaults (ring on, dumps beside the trace dir / CWD)
        bare = self._env_for(["-np", "2"])
        assert "HVTPU_FLIGHT_DIR" not in bare
        assert "HVTPU_FLIGHT_WINDOW" not in bare

    def test_env_passthrough_set_and_copy(self):
        env = self._env_for(
            ["-np", "2", "-x", "FOO=bar", "-x", "INHERITED"])
        assert env["FOO"] == "bar"
        assert env["INHERITED"] == "yes"

    def test_autotune_knobs(self):
        env = self._env_for(
            ["-np", "2", "--autotune",
             "--autotune-warmup-samples", "5",
             "--autotune-bayes-opt-max-samples", "20"])
        assert env["HVTPU_AUTOTUNE"] == "1"
        assert env["HVTPU_AUTOTUNE_WARMUP_SAMPLES"] == "5"
        assert env["HVTPU_AUTOTUNE_GP_SAMPLES"] == "20"

    def test_hostfile_reference_format(self, tmp_path):
        from horovod_tpu.runner import launch

        # a hostname CONTAINING 'slots' must still parse compactly
        hf2 = tmp_path / "hosts2"
        hf2.write_text("gpu-slots-01:8\nbare-host\n")
        assert launch.parse_hostfile(str(hf2)) == \
            "gpu-slots-01:8,bare-host:1"
        hf = tmp_path / "hosts"
        hf.write_text("# cluster\nnode1 slots=4\nnode2:2\n\n")
        assert launch.parse_hostfile(str(hf)) == "node1:4,node2:2"
        args = launch.parse_args(
            ["-np", "6", "--hostfile", str(hf), "--", "python", "x.py"])
        assert args.hosts == "node1:4,node2:2"

    def test_hostfile_and_hosts_conflict(self, tmp_path):
        import pytest as _pytest

        from horovod_tpu.runner import launch

        hf = tmp_path / "hosts"
        hf.write_text("node1 slots=4\n")
        with _pytest.raises(SystemExit):
            launch.parse_args(["-np", "2", "--hostfile", str(hf),
                               "-H", "a:2", "--", "python", "x.py"])

    def test_check_build_runs(self, capsys):
        from horovod_tpu.runner import launch

        assert launch.main(["-cb"]) == 0
        out = capsys.readouterr().out
        assert "Available frameworks" in out and "JAX" in out

    def test_version_runs(self, capsys):
        from horovod_tpu.runner import launch

        assert launch.main(["--version"]) == 0
        assert capsys.readouterr().out.strip()


class TestSignedFunctionChannel:
    """HMAC signing of run()'s pickle channel (parity:
    horovod/runner/common/util/secret.py): tampered payloads must fail
    CLOSED — never unpickled."""

    def test_sign_verify_roundtrip(self):
        from horovod_tpu.runner import secret

        key = secret.make_secret_key()
        blob = b"payload-bytes"
        assert secret.verify(key, secret.sign(key, blob)) == blob

    def test_tampered_blob_rejected(self):
        from horovod_tpu.runner import secret

        key = secret.make_secret_key()
        signed = bytearray(secret.sign(key, b"payload"))
        signed[-1] ^= 0x01
        with pytest.raises(secret.SignatureError):
            secret.verify(key, bytes(signed))

    def test_wrong_key_rejected(self):
        from horovod_tpu.runner import secret

        signed = secret.sign(secret.make_secret_key(), b"x")
        with pytest.raises(secret.SignatureError):
            secret.verify(secret.make_secret_key(), signed)

    def test_worker_refuses_tampered_fn_file(self, tmp_path,
                                             monkeypatch):
        """run_task fails closed on a flipped byte: the tampered pickle
        is never loaded and the rank reports the signature error."""
        import pickle

        from horovod_tpu.runner import run_task, secret

        key = secret.make_secret_key()
        monkeypatch.setenv(secret.ENV_KEY, key)
        monkeypatch.setenv("HVTPU_RANK", "0")
        import cloudpickle

        blob = cloudpickle.dumps((lambda: 42, (), {}))
        signed = bytearray(secret.sign(key, blob))
        signed[40] ^= 0xFF  # flip a payload byte past the digest
        fn_path = tmp_path / "fn.pkl"
        fn_path.write_bytes(bytes(signed))
        out_dir = tmp_path / "out"
        out_dir.mkdir()
        code = run_task.main(str(fn_path), str(out_dir))
        assert code == 1
        payload = pickle.loads(secret.verify(
            key, (out_dir / "rank_0.pkl").read_bytes()))
        assert payload[0] is False
        assert "SignatureError" in payload[1]

    def test_worker_refuses_missing_key(self, tmp_path, monkeypatch):
        from horovod_tpu.runner import run_task, secret

        monkeypatch.delenv(secret.ENV_KEY, raising=False)
        monkeypatch.delenv(secret.ENV_KEY_FILE, raising=False)
        monkeypatch.setenv("HVTPU_RANK", "0")
        fn_path = tmp_path / "fn.pkl"
        fn_path.write_bytes(b"whatever-bytes-no-signature-possible")
        out_dir = tmp_path / "out"
        out_dir.mkdir()
        assert run_task.main(str(fn_path), str(out_dir)) == 1

    def test_signed_channel_end_to_end(self):
        """run() works end-to-end with signing on (the launcher-side
        rejection of foreign result files is the wrong-key unit test:
        the launcher verifies every rank_N.pkl with the job key before
        unpickling)."""
        from horovod_tpu import runner as runner_mod

        def body():
            return "ok"

        assert runner_mod.run(body, np=1, cpu_devices=1) == ["ok"]

    def test_bad_rank_signature_reported_with_context(self, monkeypatch):
        """A result file failing verification surfaces through RunError
        WITH the rank and the other ranks' statuses — not a bare
        SignatureError that aborts collection of the remaining ranks."""
        from horovod_tpu import runner as runner_mod
        from horovod_tpu.runner import secret

        real_verify = secret.verify
        calls = {"n": 0}

        def flaky_verify(key, signed):
            # launcher-side collection reads rank_0 first; fail it only
            calls["n"] += 1
            if calls["n"] == 1:
                raise secret.SignatureError("digest mismatch (test)")
            return real_verify(key, signed)

        def body():
            return "ok"

        monkeypatch.setattr(secret, "verify", flaky_verify)
        with pytest.raises(runner_mod.RunError) as ei:
            runner_mod.run(body, np=2, cpu_devices=1)
        assert ei.value.rank == 0
        assert "signature verification" in str(ei.value)
        assert "rank 1: ok" in str(ei.value)


class TestFaultSpecLaunchValidation:
    """A malformed fault spec — from --fault-spec OR an inherited
    HVTPU_FAULT_SPEC — must fail at the launcher naming the bad
    clause, before any worker spawns (a bad clause would otherwise
    kill every worker at fault-registry init, which at scale reads as
    a mysterious whole-job crash)."""

    # one malformed spec per grammar shape parse_spec rejects
    BAD_SPECS = [
        ("kv.get", "expected 'site:action"),           # no action
        ("bogus.site:error", "unknown site"),          # bad site
        ("kv.get:explode", "unknown action"),          # bad action
        ("kv.get:delay(abc)", "unknown action"),       # bad delay arg
        ("kv.get:error@prob=2.0", "bad selector"),     # prob out of range
        ("kv.get:error@times=x", "bad selector"),      # non-int times
        ("kv.get:error@rank=a", "bad selector"),       # non-int rank
        ("kv.get:error@wat=1", "unknown selector"),    # unknown selector
    ]

    @pytest.mark.parametrize("spec,msg", BAD_SPECS)
    def test_env_var_rejected_at_launch(self, spec, msg, monkeypatch,
                                        capsys):
        monkeypatch.setenv("HVTPU_FAULT_SPEC", spec)
        rc = launch_mod.main(["-np", "1", "true"])
        assert rc == 2
        err = capsys.readouterr().err
        assert "HVTPU_FAULT_SPEC" in err
        assert msg in err
        assert spec in err  # the diagnostic names the bad clause

    @pytest.mark.parametrize("spec,msg", BAD_SPECS[:2])
    def test_flag_rejected_at_launch(self, spec, msg, monkeypatch,
                                     capsys):
        monkeypatch.delenv("HVTPU_FAULT_SPEC", raising=False)
        rc = launch_mod.main(
            ["-np", "1", "--fault-spec", spec, "true"])
        assert rc == 2
        err = capsys.readouterr().err
        assert "--fault-spec" in err
        assert msg in err

    def test_valid_env_spec_reaches_workers(self, monkeypatch):
        captured = {}

        def fake_launch(command, slots, coordinator_addr, port,
                        **kwargs):
            captured["ok"] = True
            return 0

        monkeypatch.setenv("HVTPU_FAULT_SPEC",
                           "kv.get:error@prob=0.1;worker.step:kill@rank=3")
        monkeypatch.setattr(launch_mod, "launch_workers", fake_launch)
        assert launch_mod.main(["-np", "1", "true"]) == 0
        assert captured["ok"]
