"""DataFrame materialization for the estimators — the Petastorm seam.

Parity surface: ``horovod/spark/common/util.py``
(``prepare_data`` / ``check_validation``) — the reference materializes
the input DataFrame to a Parquet intermediate in the Store and streams
it back per-rank through Petastorm readers.  Petastorm is scoped out
(SURVEY §7.3); the TPU-native replacement materializes to columnar
``.npz`` in the Store (static shapes, zero-copy mmap back) and shards
rows **rank-strided** across workers — the DistributedSampler
convention the torch frontend already follows.

Accepted inputs: pandas DataFrame, dict of column arrays, or a pyspark
DataFrame when pyspark is importable (collected via ``toPandas()`` —
local-mode scale, same as the reference's CI).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

TRAIN_NPZ = "train.npz"
VAL_NPZ = "val.npz"


def to_columns(df, cols: List[str]) -> Dict[str, np.ndarray]:
    """Extract named columns as numpy arrays from any accepted
    DataFrame shape.  Object columns of fixed-length sequences stack
    into one static-shaped array (ragged rows are rejected — XLA wants
    static shapes, and the reference's Parquet path is rectangular
    too)."""
    try:
        import pyspark.sql as psql  # noqa: F401

        if hasattr(df, "toPandas"):
            df = df.toPandas()
    except ImportError:
        pass
    out = {}
    for c in cols:
        if isinstance(df, dict):
            col = np.asarray(df[c])
        else:  # pandas
            col = df[c].to_numpy()
        if col.dtype == object:
            try:
                col = np.stack([np.asarray(v) for v in col])
            except ValueError as e:
                raise ValueError(
                    f"column {c!r} holds ragged sequences; estimator "
                    "columns must be rectangular (static shapes)"
                ) from e
        out[c] = col
    return out


def split_validation(
    columns: Dict[str, np.ndarray],
    validation,
    seed: Optional[int],
) -> Tuple[Dict[str, np.ndarray], Optional[Dict[str, np.ndarray]]]:
    """Reference semantics (``check_validation``): ``validation`` is a
    float fraction (random row split) or the NAME of an indicator
    column whose nonzero rows are the validation set."""
    if validation is None:
        return columns, None
    n = len(next(iter(columns.values())))
    if isinstance(validation, str):
        if validation not in columns:
            raise ValueError(
                f"validation column {validation!r} not among the "
                f"materialized columns {sorted(columns)}; include it "
                "in feature/label extraction")
        mask = columns[validation].astype(bool)
    elif isinstance(validation, float):
        if not 0.0 < validation < 1.0:
            raise ValueError(
                f"validation fraction must be in (0, 1), got {validation}")
        rng = np.random.RandomState(0 if seed is None else seed)
        mask = rng.rand(n) < validation
    else:
        raise TypeError(
            f"validation must be a float fraction or an indicator "
            f"column name, got {type(validation).__name__}")
    train = {c: v[~mask] for c, v in columns.items()}
    val = {c: v[mask] for c, v in columns.items()}
    return train, val


def materialize(
    df,
    store,
    feature_cols: List[str],
    label_cols: List[str],
    validation=None,
    sample_weight_col: Optional[str] = None,
    seed: Optional[int] = None,
) -> Tuple[int, int]:
    """Write the train (and optional val) split as columnar npz into
    the Store's intermediate paths + a JSON metadata sidecar; returns
    ``(n_train, n_val)``."""
    cols = list(dict.fromkeys(
        feature_cols + label_cols
        + ([sample_weight_col] if sample_weight_col else [])
        + ([validation] if isinstance(validation, str) else [])))
    columns = to_columns(df, cols)
    train, val = split_validation(columns, validation, seed)
    if isinstance(validation, str):  # indicator column is not a feature
        train.pop(validation, None)
        if val:
            val.pop(validation, None)

    train_dir = store.get_train_data_path()
    os.makedirs(train_dir, exist_ok=True)
    np.savez(os.path.join(train_dir, TRAIN_NPZ), **train)
    n_val = 0
    if val is not None and len(next(iter(val.values()))):
        val_dir = store.get_val_data_path()
        os.makedirs(val_dir, exist_ok=True)
        np.savez(os.path.join(val_dir, VAL_NPZ), **val)
        n_val = len(next(iter(val.values())))
    meta = {
        "n_train": int(len(next(iter(train.values())))),
        "n_val": int(n_val),
        "feature_cols": feature_cols,
        "label_cols": label_cols,
        "sample_weight_col": sample_weight_col,
        "schema": {c: {"dtype": str(v.dtype), "shape": list(v.shape[1:])}
                   for c, v in train.items()},
    }
    store.write_text(store.get_data_metadata_path(), json.dumps(meta))
    return meta["n_train"], n_val


def load_shard(path: str, npz_name: str, rank: int, size: int,
               ) -> Dict[str, np.ndarray]:
    """This rank's strided row slice of a materialized split
    (``rows[rank::size]`` — every rank gets ⌈n/size⌉ or ⌊n/size⌋ rows,
    the DistributedSampler convention, so no rank starves and epochs
    stay near-lockstep)."""
    with np.load(os.path.join(path, npz_name), mmap_mode="r") as z:
        return {c: np.asarray(z[c][rank::size]) for c in z.files}
