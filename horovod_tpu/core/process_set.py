"""Process sets: named subsets of ranks with their own communicators.

Parity surface: the reference's ``horovod/common/process_set.cc``
(``ProcessSetTable``) and ``horovod/common/process_sets.py`` — named rank
subsets, each with its own controller + communicator, addressed by id in
every collective (``process_set_id`` argument).

TPU-native mapping (SURVEY.md §5.8): a process set is a **sub-mesh**.
Instead of lazily creating an NCCL communicator per (process set, device)
pair, we lazily build:

* an eager sub-mesh over the member processes' devices (the data plane
  for eager collectives restricted to the set), and
* ``axis_index_groups`` partitions for in-jit SPMD reductions, so a
  single compiled program can reduce within the set while non-members
  sit in singleton groups.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np
from jax.sharding import Mesh

from .topology import PROC_AXIS, Topology


class _CallableInt(int):
    """An int answering reference method-call syntax: upstream's
    ProcessSet exposes size()/rank() as METHODS while this engine
    reads them as values — ``x`` and ``x()`` both yield the count."""

    __slots__ = ()

    def __call__(self) -> int:
        return int(self)


class ProcessSet:
    """A subset of ranks (processes) that collectives can be scoped to.

    ``ranks=None`` denotes the global set (all ranks).
    """

    def __init__(self, ranks: Optional[Sequence[int]] = None):
        self.ranks: Optional[List[int]] = (
            sorted(set(ranks)) if ranks is not None else None
        )
        self.process_set_id: Optional[int] = None
        self._topology: Optional[Topology] = None
        self._lock = threading.Lock()
        self._proc_mesh: Optional[Mesh] = None

    def _bind(self, process_set_id: int, topology: Topology, world_size: int):
        self.process_set_id = process_set_id
        self._topology = topology
        if self.ranks is None:
            self.ranks = list(range(world_size))

    @property
    def size(self) -> "_CallableInt":
        """Member count.  An int that is ALSO callable: the engine
        reads ``ps.size`` as a value while reference-ported user code
        calls ``ps.size()`` (upstream ProcessSet.size is a method) —
        both work."""
        assert self.ranks is not None
        return _CallableInt(len(self.ranks))

    @property
    def rank(self):
        """THIS process's rank within the set (parity:
        ProcessSet.rank()): a callable int for members, None when this
        process is not in the set (upstream returns None likewise).
        Works both as ``ps.rank`` and reference-style ``ps.rank()``."""
        from . import state as _state

        st = _state.require_init("ProcessSet.rank")
        r = self.rank_in_set(st.rank)
        return None if r < 0 else _CallableInt(r)

    def rank_in_set(self, global_rank: int) -> int:
        """Position of ``global_rank`` within the set (-1 if absent)."""
        assert self.ranks is not None
        try:
            return self.ranks.index(global_rank)
        except ValueError:
            return -1

    def included(self, global_rank: Optional[int] = None) -> bool:
        """Membership test (parity: ProcessSet.included() asks about
        THIS process; passing a global rank asks about that one)."""
        if global_rank is None:
            from . import state as _state

            global_rank = _state.require_init("ProcessSet.included").rank
        return self.rank_in_set(global_rank) >= 0

    def proc_mesh(self) -> Mesh:
        """One-device-per-member-process mesh (eager data plane).

        Lazily created and cached, mirroring the reference's lazy NCCL
        communicator creation per process set.
        """
        assert self._topology is not None and self.ranks is not None
        with self._lock:
            if self._proc_mesh is None:
                devs = [self._topology.process_device(r) for r in self.ranks]
                self._proc_mesh = Mesh(
                    np.asarray(devs, dtype=object), (PROC_AXIS,)
                )
            return self._proc_mesh

    def device_groups(self) -> Optional[List[List[int]]]:
        """``axis_index_groups`` partition of the world-mesh axis.

        Member processes' devices form one group; non-member devices are
        grouped into equal-size chunks when the counts divide evenly
        (XLA requires equal-size replica groups for gather/scatter-shaped
        collectives), falling back to singleton groups otherwise — which
        psum/pmin/pmax accept but spmd.allgather/alltoall/reducescatter
        reject with a clear error.  Non-members' results are their own
        values and are expected to be unused.  Returns None for the
        global set.
        """
        assert self._topology is not None and self.ranks is not None
        devices = self._topology.devices
        if len(self.ranks) == len({d.process_index for d in devices}):
            return None
        member = [
            i for i, d in enumerate(devices) if d.process_index in self.ranks
        ]
        others = [
            i for i, d in enumerate(devices)
            if d.process_index not in self.ranks
        ]
        m = len(member)
        if m and len(others) % m == 0:
            rest = [others[i : i + m] for i in range(0, len(others), m)]
        else:
            rest = [[i] for i in others]
        return [member] + rest

    def __repr__(self):
        return (
            f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})"
        )


def participant_rank(process_set) -> int:
    """This process's rank WITHIN the collective's span: its set-relative
    index, or the global rank when no set is given (shared by frontends
    needing per-rank offsets, e.g. the allgather gradient slice)."""
    from . import state as core_state

    st = core_state.require_init("process-set lookup")
    if process_set is None:
        return st.rank
    if isinstance(process_set, int):
        process_set = st.process_set_table.get(process_set)
    return process_set.rank_in_set(st.rank)


def participant_count(process_set) -> int:
    """Number of ranks a collective spans: the process set's size, or
    the world when none is given.  Shared by every frontend so the
    resolution rule cannot drift between them."""
    from . import state as core_state

    if process_set is None:
        return core_state.global_state().size
    if isinstance(process_set, int):
        st = core_state.require_init("process-set lookup")
        return st.process_set_table.get(process_set).size
    return process_set.size


class ProcessSetTable:
    """Registry of process sets; id 0 is always the global set.

    Parity: ``ProcessSetTable`` in horovod/common/process_set.cc and the
    Python-side registry in horovod/common/process_sets.py.
    """

    def __init__(self, topology: Topology, world_size: int):
        self._lock = threading.Lock()
        self._topology = topology
        self._world_size = world_size
        self._table: Dict[int, ProcessSet] = {}
        self._next_id = 0
        self.global_process_set = ProcessSet(None)
        self._register(self.global_process_set)

    def _register(self, ps: ProcessSet) -> int:
        psid = self._next_id
        self._next_id += 1
        ps._bind(psid, self._topology, self._world_size)
        self._table[psid] = ps
        return psid

    def add(self, ps: ProcessSet) -> int:
        with self._lock:
            if ps.ranks is not None:
                bad = [r for r in ps.ranks if not 0 <= r < self._world_size]
                if bad:
                    raise ValueError(
                        f"ranks {bad} out of range for world size "
                        f"{self._world_size}"
                    )
                for existing in self._table.values():
                    if existing.ranks == sorted(set(ps.ranks)):
                        raise ValueError(
                            f"a process set with ranks {ps.ranks} already "
                            f"exists (id {existing.process_set_id})"
                        )
            return self._register(ps)

    def remove(self, psid: int):
        with self._lock:
            if psid == 0:
                raise ValueError("cannot remove the global process set")
            if psid not in self._table:
                raise ValueError(f"unknown process set id {psid}")
            del self._table[psid]

    def get(self, psid: int) -> ProcessSet:
        with self._lock:
            if psid not in self._table:
                raise ValueError(f"unknown process set id {psid}")
            return self._table[psid]

    def ids(self) -> List[int]:
        with self._lock:
            return sorted(self._table)
