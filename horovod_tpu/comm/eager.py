"""Eager process-level collectives: one process = one Horovod rank.

Parity surface: the synchronous eager API of the reference
(hvd.allreduce/allgather/broadcast/alltoall/reducescatter on concrete
tensors, horovod/torch/mpi_ops.py + the enqueue path in
horovod/common/operations.cc).

TPU-native design: each participating process contributes its local
tensor; we assemble a global ``jax.Array`` sharded one-shard-per-process
over the process mesh (``Topology.proc_mesh``), run a *jitted*
``shard_map`` collective over it (XLA moves the bytes over ICI/DCN), and
return the locally-addressable result.  The XLA runtime's async dispatch
plays the role of the reference's background thread: the returned array
is a future, and blocking happens only at ``synchronize``.

Ordering contract (same as any SPMD system): all member processes must
issue the same sequence of eager collectives.  The reference relaxes
this with its controller negotiation; horovod_tpu restores that
flexibility for the *async* API via the eager mini-controller
(horovod_tpu.eager), which re-orders enqueues into an agreed schedule
before they reach this layer.

Single-process mode (P == 1, any number of local devices) degenerates to
local math, so the same user program runs unmodified from a laptop to a
pod — collectives over local devices belong to the SPMD layer instead.

Pod shape (P > 1, D > 1 local devices): the eager data plane stays
process-granularity — rank = process.  Every bulk collective —
``allreduce``, ``broadcast``, ``allgather``, ``reducescatter``
(Sum/Average), ``alltoall`` — shards payloads of at least
``_MULTIDEV_MIN_BYTES`` across ALL D local devices
(``_multidev_mesh``: D parallel lanes, each moving 1/D of the payload
— same numerics, D× the link bandwidth;
``HVTPU_EAGER_MULTIDEVICE=0`` disables, snapshotted at init).  Smaller
payloads ride the process's FIRST local device
(``Topology.proc_mesh``); either way the remaining devices are
primarily the jit/SPMD path's compute surface (``world_mesh`` spans
all P×D devices).  ``init()`` logs the layout at INFO so a D>1
profile of an eager-only program reads as designed behavior.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import faults
from ..core import state as core_state
from ..core.topology import DCN_AXIS, ICI_AXIS, LDEV_AXIS, PROC_AXIS
from ..obs import metrics as obs_metrics
from ..obs import stepprof
from ..obs import tracing
from . import packing
from . import spmd
from . import stall
from .compression import NoneCompressor
from .reduce_ops import ReduceOp, normalize_op


# Threads executing a controller-agreed schedule: their async ops
# already passed the ``collective.pre`` site at the issuance boundary
# (EagerController.enqueue), so the dispatch below must not fire it a
# second time — one firing per op keeps ``count=N`` staging exact.
_EXEC_TL = threading.local()


class _ControllerExecution:
    def __enter__(self):
        _EXEC_TL.active = True
        return self

    def __exit__(self, *exc):
        _EXEC_TL.active = False
        return False


def controller_execution():
    """Context manager the eager mini-controller's executor wraps its
    data-plane dispatch in (see ``_record_collective``)."""
    return _ControllerExecution()


def _record_collective(kind: str, x, p: int, compression=None,
                       pset=None):
    """Registry bookkeeping for one eager collective: per-kind count,
    payload bytes before compression, and the bytes this rank actually
    contributes to the wire after compression/quantization (incl. the
    int8 path's fp32 block-scale sidecar).  P==1 worlds move nothing,
    so only the op count is recorded.  Covers the sync API and the
    async controller's execution (which dispatches through these same
    functions).  Cost: a few dict updates, ~1 us.

    Also the ``collective.pre`` fault-injection site (core/faults.py):
    every eager collective passes here before dispatch, so an armed
    clause can delay/error/kill a rank right at the dispatch boundary
    — the divergence class the stall watchdog exists to catch — or
    ``corrupt`` this rank's INPUT tensor (NaN-poison rides the wire to
    every peer, exercising the optimizer's coordinated non-finite
    guard).  Returns the (possibly poisoned) tensor.  The empty-spec
    cost is one module-attribute read.

    Async ops fire the site at their issuance boundary instead
    (EagerController.enqueue) — that is where a delayed rank is
    observable as coordinator arrival skew, the straggler class the
    anomaly plane names ranks for — and the executor suppresses the
    duplicate dispatch-time firing via ``controller_execution``."""
    if faults.ACTIVE and not getattr(_EXEC_TL, "active", False):
        x = faults.inject_tensor("collective.pre", x, pset=pset,
                                 detail=kind)
    obs_metrics.op_counter(kind).inc()
    if p <= 1:
        return x
    nbytes = int(x.nbytes)
    obs_metrics.TENSOR_BYTES.inc(nbytes)
    wire_nbytes = nbytes
    if compression is not None:
        try:
            wd = jnp.dtype(compression.wire_dtype(x.dtype))
            wire_nbytes = int(x.size) * wd.itemsize
            if wd == jnp.dtype(jnp.int8):
                from .compression import Int8Compressor

                wire_nbytes += 4 * (
                    -(-int(x.size) // Int8Compressor.BLOCK))
        except Exception:
            pass
    obs_metrics.WIRE_BYTES.inc(wire_nbytes)
    return x


def _post_collective(kind: str, out, pset=None):
    """The ``collective.post`` fault-injection site: fires after the
    collective completed, so a ``corrupt`` clause poisons THIS RANK'S
    RESULT only — manufacturing exactly the silent cross-rank
    divergence the parameter audit (core/audit.py) exists to catch."""
    if faults.ACTIVE:
        return faults.inject_tensor("collective.post", out, pset=pset,
                                    detail=kind)
    return out


# --------------------------------------------------------------------------
# plumbing
# --------------------------------------------------------------------------

def _resolve_process_set(process_set):
    st = core_state.require_init("eager collectives")
    if process_set is None:
        return st, st.process_set_table.global_process_set
    if isinstance(process_set, int):
        return st, st.process_set_table.get(process_set)
    return st, process_set


def _local_device(mesh: Mesh) -> jax.Device:
    for d in mesh.devices.flat:
        if d.process_index == jax.process_index():
            return d
    raise RuntimeError("calling process is not a member of this process set")


@functools.lru_cache(maxsize=None)
def _mesh_plumbing(mesh: Mesh):
    """Per-mesh staging artifacts for the flat stacker: (sharding,
    local device).  Meshes are cached on their ProcessSet (and in
    _jitted's key), so this is a handful of entries per world — but the
    NamedSharding construction and the local-device scan over
    mesh.devices.flat used to run on EVERY eager op, a measurable slice
    of the small-op dispatch floor (the torch DistributedOptimizer
    bucket pattern re-stages the same mesh every step)."""
    spec = P(tuple(mesh.axis_names)) if len(mesh.axis_names) > 1 \
        else P(mesh.axis_names[0])
    return NamedSharding(mesh, spec), _local_device(mesh)


@functools.lru_cache(maxsize=128)
def _f32_scalar(value: float):
    """Cached device scalar for pre/postscale factors (almost always
    1.0): jnp.asarray per op is a host->device transfer on the
    dispatch critical path."""
    return jnp.asarray(value, jnp.float32)


def _stack_global(x, mesh: Mesh):
    """Global (P, *shape) array, shard p = process p's tensor."""
    p = mesh.devices.size
    sharding, local_dev = _mesh_plumbing(mesh)
    local = jax.device_put(x[None], local_dev)
    return jax.make_array_from_single_device_arrays(
        (p,) + tuple(x.shape), sharding, [local]
    )


# Below this payload size the lane path is pure overhead (pad-to-D, D
# device_puts, an extra all_gather) with no bandwidth to parallelize —
# e.g. broadcast_object's 4-byte size header.  All ranks see the same
# tensor size for a given collective, so size-based routing is
# rank-consistent.
_MULTIDEV_MIN_BYTES = 64 * 1024


def _multidev_mesh_or_none(ps):
    """(proc, ldev) mesh for multi-lane eager allreduce, or None.

    With D > 1 local devices per process, a single-transport-device
    eager allreduce pushes the whole payload through ONE device's
    links; sharding each process's contribution across its D local
    devices gives D parallel reduction lanes (each lane psums 1/D of
    the payload with its counterparts) — same numerics, D× the link
    bandwidth.  Requires a uniform local device count across the set's
    processes; disabled via ``HVTPU_EAGER_MULTIDEVICE=0``, which is
    SNAPSHOTTED at init (all processes must agree or they would
    compile mismatched collective programs and hang — the launcher
    distributes the env uniformly, like HIERARCHICAL_ALLREDUCE).
    """
    cfg = core_state.global_state().config
    if cfg is None or not cfg.eager_multidevice:
        return None
    mesh = getattr(ps, "_multidev_mesh", None)
    if mesh is not None:
        return mesh if mesh is not False else None
    # collect every device of every member process
    proc_devs = {}
    member_procs = {d.process_index for d in ps.proc_mesh().devices.flat}
    for d in jax.devices():
        if d.process_index in member_procs:
            proc_devs.setdefault(d.process_index, []).append(d)
    counts = {len(v) for v in proc_devs.values()}
    if len(counts) != 1 or counts == {1}:
        ps._multidev_mesh = False
        return None
    grid = np.asarray(
        [sorted(proc_devs[p], key=lambda d: d.id)
         for p in sorted(proc_devs)],
        dtype=object,
    )
    mesh = Mesh(grid, (PROC_AXIS, LDEV_AXIS))
    ps._multidev_mesh = mesh
    return mesh


@functools.lru_cache(maxsize=None)
def _mesh_plumbing_md(mesh: Mesh):
    """Per-mesh lane-stacking artifacts: (flat sharding, row-structured
    sharding, p_count, d_count, local_row) — same memoization rationale
    as :func:`_mesh_plumbing`."""
    d_count = mesh.devices.shape[1]
    pid = jax.process_index()
    for r, row in enumerate(mesh.devices):
        if row[0].process_index == pid:
            return (NamedSharding(mesh, P(PROC_AXIS, LDEV_AXIS)),
                    NamedSharding(mesh, P(PROC_AXIS, None, LDEV_AXIS)),
                    mesh.devices.shape[0], d_count, r)
    raise RuntimeError("process not a member of the multidev mesh")


def _lane_layout(mesh: Mesh, inner: int):
    """Shared lane-stacking bookkeeping: (p_count, d_count, chunk,
    local_row) for this process, with ``chunk`` the ceil-div lane slice
    of ``inner`` elements.  One implementation so the flat and
    row-structured stackers can never disagree on membership or
    padding."""
    _, _, p_count, d_count, local_row = _mesh_plumbing_md(mesh)
    chunk = -(-inner // d_count)
    return p_count, d_count, chunk, local_row


def _stack_global_multidev(x, mesh: Mesh):
    """Global (P, D, chunk) f-contiguous array: shard (p, d) is process
    p's d-th slice of its flattened (padded) tensor, resident on that
    process's d-th device.  Returns (stacked, flat_size)."""
    flat = x.reshape(-1)
    size = flat.shape[0]
    p_count, d_count, chunk, local_row = _lane_layout(mesh, size)
    pad = chunk * d_count - size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    sharding = _mesh_plumbing_md(mesh)[0]
    locals_ = [
        jax.device_put(
            flat[d * chunk:(d + 1) * chunk][None, None],
            mesh.devices[local_row][d],
        )
        for d in range(d_count)
    ]
    stacked = jax.make_array_from_single_device_arrays(
        (p_count, d_count, chunk), sharding, locals_
    )
    return stacked, size


def _stack_global_multidev_rows(x, rows: int, mesh: Mesh):
    """Row-structured lane stacking: ``x`` reshaped to (rows, inner)
    with each row's inner bytes split across the D local devices →
    global (P, rows, D, chunk) array sharded (PROC, None, LDEV, None);
    shard (p, d) holds process p's lane-d slice of every row.  Used by
    the lane reducescatter (rows = destination ranks) and alltoall
    (rows = destination chunks).  Returns (stacked, inner_size)."""
    flat = x.reshape(rows, -1)
    inner = flat.shape[1]
    p_count, d_count, chunk, local_row = _lane_layout(mesh, inner)
    pad = chunk * d_count - inner
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    sharding = _mesh_plumbing_md(mesh)[1]
    locals_ = [
        jax.device_put(
            flat[:, d * chunk:(d + 1) * chunk][None, :, None, :],
            mesh.devices[local_row][d],
        )
        for d in range(d_count)
    ]
    stacked = jax.make_array_from_single_device_arrays(
        (p_count, rows, d_count, chunk), sharding, locals_
    )
    return stacked, inner


def _hierarchical_mesh_or_none(st, ps, p: int):
    """The (dcn, ici) eager mesh when hierarchical allreduce applies:
    global process set, a true 2-D (multi-host AND multi-process-per-
    host) topology covering all ranks.

    Rank assignment is host-major (runner/hosts.py), so reshaping the
    rank-ordered device list to (cross_size, local_size) puts same-host
    processes along the fast ``ici`` axis (parity: the local/cross
    communicator split of NCCLHierarchicalAllreduce).  Cached on the
    ProcessSet instance (like its flat proc mesh), so a shutdown/init
    cycle — which rebuilds the table — can never serve a mesh of dead
    device objects.
    """
    cfg = st.config
    if (cfg is None or not cfg.hierarchical_allreduce
            or ps.process_set_id != 0
            # launcher-certified uniform layout: rank-local sizes alone
            # can't prove every host has the same slot count, and a
            # non-uniform job must not split ranks between hierarchical
            # and flat programs
            or cfg.uniform_local_size <= 1
            or st.local_size != cfg.uniform_local_size
            or st.cross_size <= 1
            or st.cross_size * st.local_size != p):
        return None
    mesh = getattr(ps, "_hier_proc_mesh", None)
    if mesh is None:
        flat = ps.proc_mesh().devices.reshape(-1)
        grid = flat.reshape(st.cross_size, st.local_size)
        mesh = Mesh(grid, (DCN_AXIS, ICI_AXIS))
        ps._hier_proc_mesh = mesh
    return mesh


@functools.lru_cache(maxsize=None)
def _jitted(kind: str, mesh: Mesh, static: Tuple):
    """Compiled collective over the process mesh. jit's own cache handles
    shapes/dtypes; this cache handles (mesh, op-kind, static config) —
    the analog of the reference's lazily-created per-(process set, op)
    communicators.
    """
    if kind == "allreduce":
        (rop, compression) = static

        def fn(stacked, prescale, postscale):
            def body(shard, pre, post):
                x = shard[0]
                x = x * pre.astype(x.dtype)
                out = spmd.allreduce(
                    x, axis_name=PROC_AXIS, op=rop, compression=compression
                )
                return out * post.astype(out.dtype)

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS), P(), P()),
                out_specs=P(),
                check_vma=False,
            )(stacked, prescale, postscale)

        return jax.jit(fn)

    if kind == "allreduce_multidev":
        # D parallel reduction lanes: lane d psums 1/D of the payload
        # over the proc axis, then the lanes all_gather so every
        # device (and thus the process) holds the full result.
        (rop, compression) = static

        def fn(stacked, prescale, postscale):
            def body(shard, pre, post):
                x = shard[0, 0]
                x = x * pre.astype(x.dtype)
                out = spmd.allreduce(
                    x, axis_name=PROC_AXIS, op=rop,
                    compression=compression,
                )
                out = out * post.astype(out.dtype)
                return lax.all_gather(out, LDEV_AXIS, tiled=True)

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS, LDEV_AXIS), P(), P()),
                out_specs=P(),
                check_vma=False,
            )(stacked, prescale, postscale)

        return jax.jit(fn)

    if kind == "allreduce_hier":
        # Two-stage reduce over the (dcn, ici) grid: combine within a
        # host/slice first (fast links), then across hosts (parity:
        # NCCLHierarchicalAllreduce's intra-node reduce + inter-node
        # allreduce + intra-node broadcast, which XLA lowers from the
        # two psums).
        (rop, compression) = static

        def fn(stacked, prescale, postscale):
            def body(shard, pre, post):
                # dim0 is sharded over BOTH mesh axes -> local (1, ...)
                x = shard[0]
                x = x * pre.astype(x.dtype)
                out = spmd.allreduce(
                    x, axis_name=ICI_AXIS, op=rop, compression=compression
                )
                # the cross-host stage is the slow link hierarchical
                # allreduce exists to economize — compress it too
                out = spmd.allreduce(
                    out, axis_name=DCN_AXIS, op=rop,
                    compression=compression,
                )
                # AVERAGE: each stage divides by its own axis size; the
                # product is the full world divisor, nothing to fix.
                return out * post.astype(out.dtype)

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P((DCN_AXIS, ICI_AXIS)), P(), P()),
                out_specs=P(),
                check_vma=False,
            )(stacked, prescale, postscale)

        return jax.jit(fn)

    if kind == "allreduce_hier_adasum":
        # Hierarchical Adasum (parity: adasum_gpu_operations.cc —
        # local reduce within the node, Adasum across node leaders,
        # result shared back): intra-host SUM over the fast ici links,
        # then the scale-invariant combine only across hosts.  As in
        # the reference, the local stage is a SUM — with k slots per
        # host the effective per-host gradient is k× a single worker's,
        # and learning-rate scaling is the user's documented
        # responsibility.
        (compression,) = static

        def fn(stacked, prescale, postscale):
            def body(shard, pre, post):
                from .adasum import adasum_reduce

                x = shard[0]
                x = x * pre.astype(x.dtype)
                x = lax.psum(x, ICI_AXIS)
                wire, cctx = compression.compress(x)
                out = adasum_reduce(
                    wire, DCN_AXIS, lax.axis_size(DCN_AXIS)
                )
                out = compression.decompress(out, cctx)
                return out * post.astype(out.dtype)

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P((DCN_AXIS, ICI_AXIS)), P(), P()),
                out_specs=P(),
                check_vma=False,
            )(stacked, prescale, postscale)

        return jax.jit(fn)

    if kind == "allgather":

        def fn(stacked):
            def body(shard):
                return lax.all_gather(shard, PROC_AXIS, tiled=True)

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS),),
                out_specs=P(),
                check_vma=False,
            )(stacked)

        return jax.jit(fn)

    if kind == "allgather_multidev":
        # lane-parallel allgather: lane d gathers every process's d-th
        # payload slice over the proc links (1/D of the bytes per
        # lane), then the lanes exchange locally so every device — and
        # thus the process — holds the full (P, payload) result.
        def fn(stacked):
            def body(shard):
                x = shard[0, 0]                      # (chunk,)
                per_lane = lax.all_gather(
                    x, PROC_AXIS, tiled=False)       # (P, chunk)
                lanes = lax.all_gather(
                    per_lane, LDEV_AXIS, tiled=False)  # (D, P, chunk)
                return jnp.transpose(lanes, (1, 0, 2)).reshape(
                    lanes.shape[1], -1)              # (P, D*chunk)

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS, LDEV_AXIS),),
                out_specs=P(),
                check_vma=False,
            )(stacked)

        return jax.jit(fn)

    if kind == "reducescatter_multidev":
        # lane-parallel reduce-scatter: lane d psum-scatters its 1/D
        # slice of every destination chunk over the proc links, then
        # the lanes reassemble this process's reduced rows locally.
        (rop,) = static

        def fn(stacked):
            def body(shard):
                x = shard[0, :, 0]                   # (P_dest, chunk)
                red = lax.psum_scatter(
                    x, PROC_AXIS, scatter_dimension=0, tiled=True,
                )                                    # (1, chunk)
                if rop == ReduceOp.AVERAGE:
                    red = red / lax.axis_size(PROC_AXIS)
                mine = lax.all_gather(
                    red[0], LDEV_AXIS, tiled=True)   # (D*chunk,)
                return mine[None]                    # (1, D*chunk)

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS, None, LDEV_AXIS),),
                out_specs=P(PROC_AXIS),
                check_vma=False,
            )(stacked)

        return jax.jit(fn)

    if kind == "alltoall_multidev":
        # lane-parallel alltoall: lane d exchanges its 1/D slice of
        # every destination chunk, then the lanes reassemble the
        # received-from-each-source payload locally.
        def fn(stacked):
            def body(shard):
                x = shard[0, :, 0]                   # (P_dst, chunk)
                ex = lax.all_to_all(
                    x, PROC_AXIS, split_axis=0, concat_axis=0,
                    tiled=True,
                )                                    # (P_src, chunk)
                lanes = lax.all_gather(
                    ex, LDEV_AXIS, tiled=False)      # (D, P_src, chunk)
                out = jnp.transpose(lanes, (1, 0, 2)).reshape(
                    lanes.shape[1], -1)              # (P_src, D*chunk)
                return out[None]

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS, None, LDEV_AXIS),),
                out_specs=P(PROC_AXIS),
                check_vma=False,
            )(stacked)

        return jax.jit(fn)

    if kind == "broadcast":
        (root_rank,) = static

        def fn(stacked):
            def body(shard):
                return spmd.broadcast(
                    shard[0], root_rank=root_rank, axis_name=PROC_AXIS
                )

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS),),
                out_specs=P(),
                check_vma=False,
            )(stacked)

        return jax.jit(fn)

    if kind == "broadcast_multidev":
        # lane-parallel broadcast: lane d moves 1/D of the root's
        # payload over the proc axis, then the lanes all_gather —
        # broadcast_parameters' fused startup buffer rides all local
        # links instead of one
        (root_rank,) = static

        def fn(stacked):
            def body(shard):
                out = spmd.broadcast(
                    shard[0, 0], root_rank=root_rank,
                    axis_name=PROC_AXIS,
                )
                return lax.all_gather(out, LDEV_AXIS, tiled=True)

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS, LDEV_AXIS),),
                out_specs=P(),
                check_vma=False,
            )(stacked)

        return jax.jit(fn)

    if kind == "reducescatter":
        (rop,) = static

        def fn(stacked):
            def body(shard):
                out = spmd.reducescatter(
                    shard[0], axis_name=PROC_AXIS, op=rop
                )
                return out[None]

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS),),
                out_specs=P(PROC_AXIS),
                check_vma=False,
            )(stacked)

        return jax.jit(fn)

    if kind == "alltoall":

        def fn(stacked):
            # stacked: (P, P, chunk, ...) — dim1 indexes destination.
            def body(shard):
                # shard: (1, P, chunk, ...) → exchange → (1, P, chunk, ...)
                # where out[0, j] is the chunk rank j sent to this rank.
                x = shard[0]
                out = lax.all_to_all(
                    x, PROC_AXIS, split_axis=0, concat_axis=0, tiled=True
                )
                return out[None]

            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(P(PROC_AXIS),),
                out_specs=P(PROC_AXIS),
                check_vma=False,
            )(stacked)

        return jax.jit(fn)

    raise ValueError(kind)


def _fetch(global_out):
    """Locally-addressable replica of a fully-replicated output."""
    return global_out.addressable_data(0)


def _allreduce_plan(st, ps, shape, dtype, nbytes, rop, compression):
    """Memoized allreduce routing: (route, mesh, jitted fn) per
    (shape, dtype, op, compression) signature, cached on the ProcessSet
    (like its meshes, so a shutdown/init cycle can never serve stale
    device objects).  The repeated same-shape op — every step of the
    torch ``DistributedOptimizer`` bucket pattern — used to re-derive
    the hierarchical/multidev/int-average routing and re-enter the
    _jitted lru on every call; in steady state this is now one dict
    hit.  Routing inputs are all pure functions of the key plus
    init-frozen config, so the cache cannot go stale within a world."""
    cache = ps.__dict__.setdefault("_eager_ar_plans", {})
    key = (shape, dtype, rop, compression)
    plan = cache.get(key)
    if plan is not None:
        return plan
    mesh = ps.proc_mesh()
    p = mesh.devices.size
    # integer AVERAGE floor-divides per stage, which differs from a
    # single flat division — stays on the flat path.  Adasum rides the
    # hierarchy only when the HOST count is a power of two (its
    # recursive doubling runs across hosts).
    int_avg = (rop == ReduceOp.AVERAGE
               and jnp.issubdtype(dtype, jnp.integer))
    hier = None if int_avg else _hierarchical_mesh_or_none(st, ps, p)
    if (rop == ReduceOp.ADASUM and hier is not None
            and st.cross_size & (st.cross_size - 1)):
        hier = None
    # int8 stays off the lane path: block-absmax quantization
    # boundaries depend on the chunking, so per-lane chunks would
    # change numerics vs the single-transport path
    md = (None if (rop == ReduceOp.ADASUM or hier is not None
                   or spmd._is_int8(compression)
                   or nbytes < _MULTIDEV_MIN_BYTES)
          else _multidev_mesh_or_none(ps))
    if md is not None:
        plan = ("md", md,
                _jitted("allreduce_multidev", md, (rop, compression)))
    elif hier is None:
        plan = ("flat", mesh,
                _jitted("allreduce", mesh, (rop, compression)))
    elif rop == ReduceOp.ADASUM:
        plan = ("hier", hier,
                _jitted("allreduce_hier_adasum", hier, (compression,)))
    else:
        plan = ("hier", hier,
                _jitted("allreduce_hier", hier, (rop, compression)))
    cache[key] = plan
    return plan


def invalidate_routing_plans() -> int:
    """Drop every ProcessSet's memoized allreduce routing plans.

    Called by the eager controller on a schedule MISPREDICT: this rank
    executed a fused grouping the coordinator did not release, so the
    re-anchored negotiation may split the same tensors into
    differently-shaped fusion groups.  The plans themselves are pure
    functions of their keys, but dropping them forces the first
    post-resync collective of each signature through the full routing
    derivation (and a fresh ``_jitted`` entry), so no dispatch reuses
    an artifact jitted for the mispredicted grouping.  The memoized
    group-unpack programs of the zero-copy fusion plane are keyed by
    the same now-suspect groupings, so they drop together.  Returns
    the number of plans dropped (0 before init — protocol-level tests
    run controllers without a world)."""
    packing.clear_unpack_cache()
    st = core_state.global_state()
    if not getattr(st, "initialized", False):
        return 0
    dropped = 0
    table = st.process_set_table
    for psid in table.ids():
        try:
            ps = table.get(psid)
        except ValueError:  # removed concurrently
            continue
        plans = ps.__dict__.pop("_eager_ar_plans", None)
        if plans:
            dropped += len(plans)
    return dropped


# --------------------------------------------------------------------------
# public eager ops
# --------------------------------------------------------------------------

def allreduce(
    tensor,
    *,
    op: Optional[ReduceOp] = None,
    average: Optional[bool] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=NoneCompressor,
    process_set=None,
    name: Optional[str] = None,
):
    rop = normalize_op(op, average)
    if rop == ReduceOp.ADASUM:
        from .spmd import _is_int8

        if _is_int8(compression):
            # same guard as spmd.allreduce, enforced size-independently
            # (a 1-process dev run must fail the same way the pod
            # does): dot products over per-rank block-scaled int8
            # codes are meaningless
            raise ValueError(
                "int8 compression cannot ride Adasum (per-rank scales "
                "would corrupt the dot products); use fp16/bf16/none"
            )
    st, ps = _resolve_process_set(process_set)
    x = jnp.asarray(tensor)
    mesh = ps.proc_mesh()
    p = mesh.devices.size
    x = _record_collective("allreduce", x, p, compression,
                           pset=ps.process_set_id)
    t_dispatch = time.monotonic()
    # Wall-clock dispatch window for the overlap profiler
    # (obs/stepprof): joins against XLA device-profile timestamps,
    # which are wall-based, so this is time.time() not monotonic.
    t_wall0 = time.time()

    timeline = st.timeline
    tname = name or f"allreduce.{x.shape}.{x.dtype}"
    if timeline is not None:
        timeline.begin(tname, "ICI_ALLREDUCE")
    # Sync-plane trace span: opens at dispatch — after
    # _record_collective, so an injected pre-collective fault delays
    # the span start and shows up as arrival skew in the merged trace.
    # Controller-driven dispatches (bypass threads) are already spanned
    # by the controller's phase chain and must not open a second span
    # under the same rank-agnostic trace id.
    traced = tracing.ACTIVE and not stall.bypass_active()
    if traced:
        tracing.op_begin(tname, "allreduce", phase=tracing.EXEC)
    try:
        # the descriptor carries the tensor NAME (not just op/shape/
        # dtype): two ranks entering different same-shaped collectives
        # must still be diagnosed as diverged (reference MessageTable
        # keys on tensor names)
        sdesc = stall.check(
            st, ps,
            f"allreduce:{tname}:{tuple(x.shape)}:{x.dtype}:{rop.name}")
        if p == 1:
            # averaging / sum over one participant is identity; fuse
            # the scales into at most ONE pass (the old code always
            # paid two).  A copy still happens at factor 1.0: callers
            # are promised a NEW tensor (the torch frontend's DLPack
            # round-trip would otherwise alias the input buffer).
            factor = prescale_factor * postscale_factor
            if factor != 1.0:
                out = x * jnp.asarray(factor, x.dtype)
            else:
                out = jnp.copy(x)
        else:
            route, rmesh, fn = _allreduce_plan(
                st, ps, tuple(x.shape), x.dtype, x.nbytes, rop,
                compression,
            )
            postprocess = None
            if route == "md":
                stacked, flat_size = _stack_global_multidev(x, rmesh)
                postprocess = (
                    lambda o: o[:flat_size].reshape(x.shape)
                )
            else:
                stacked = _stack_global(x, rmesh)
            out = _fetch(
                stall.dispatch(
                    st, ps, fn, (
                        stacked,
                        _f32_scalar(prescale_factor),
                        _f32_scalar(postscale_factor),
                    ), desc=sdesc)
            )
            if postprocess is not None:
                out = postprocess(out)
        # Amortized-watchdog mode completes the op before returning
        # (reference sync-op semantics: hvd.allreduce returns a ready
        # tensor); strict/disabled modes keep JAX's async dispatch.
        out = stall.finish(st, ps, out, sdesc)
        if timeline is not None:
            # Accurate spans need completed ops in every mode (the
            # reference's timeline also serializes op completion).
            # After the interruptible finish: block_until_ready parks
            # inside XLA, which must never precede the stall wait.
            jax.block_until_ready(out)
        obs_metrics.ALLREDUCE_LATENCY.observe(
            time.monotonic() - t_dispatch)
        return _post_collective("allreduce", out,
                                pset=ps.process_set_id)
    finally:
        t_wall1 = time.time()
        if stepprof.ACTIVE:
            # Executor-thread (controller-driven) windows are recorded
            # too: they are the wire collectives the step overlaps.
            stepprof.note_comm(tname, t_wall0, t_wall1,
                               nbytes=int(x.nbytes))
        if traced:
            # The DONE instant carries the device-joinable wall window
            # so hvtputrace overlap can align spans with xplane
            # timestamps even across monotonic/wall drift.
            tracing.op_done(tname, bytes=int(x.nbytes),
                            wall_t0_us=int(t_wall0 * 1e6),
                            wall_t1_us=int(t_wall1 * 1e6))
        if timeline is not None:
            timeline.end(tname)


def _exchange_dim0_sizes(dim0: int, mesh: Mesh, st=None,
                         ps=None) -> np.ndarray:
    """The allgather size-negotiation step (parity: the size table logic
    in horovod/common/ops/collective_operations.cc AllgatherOp).

    The np.asarray conversion would park inside XLA's uninterruptible
    wait if a peer never joined; routing through ``stall.finish`` first
    keeps the negotiation abortable under the amortized watchdog."""
    stacked = _stack_global(jnp.asarray(dim0, jnp.int32), mesh)
    fn = _jitted("allgather", mesh, ())
    if st is not None and ps is not None:
        out = _fetch(stall.dispatch(st, ps, fn, (stacked,)))
        out = stall.finish(st, ps, out)
    else:
        out = _fetch(fn(stacked))
    return np.asarray(out)


def grouped_allreduce(
    tensors,
    *,
    op=None,
    average=None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=NoneCompressor,
    process_set=None,
):
    """Reduce a list of tensors as one fused unit (parity:
    hvd.grouped_allreduce / group_table.cc).

    Single source of the fuse policy shared with spmd.grouped_allreduce:
    Sum/Average pack into one flat wire buffer; Min/Max/Product/Adasum
    keep per-tensor semantics.
    """
    from .packing import pack_flat, unpack_flat

    rop = normalize_op(op, average)
    tensors = list(tensors)
    if not tensors:
        return []
    kwargs = dict(
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
        compression=compression,
        process_set=process_set,
    )
    if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        return [allreduce(t, op=rop, **kwargs) for t in tensors]
    flat, specs = pack_flat(tensors)
    red = allreduce(flat, op=rop, **kwargs)
    return unpack_flat(red, specs)


def allgather(tensor, *, process_set=None, name: Optional[str] = None):
    """Concatenate per-rank tensors along dim 0; ranks may differ in dim 0
    (sizes are negotiated first, like the reference's allgather).
    """
    st, ps = _resolve_process_set(process_set)
    x = jnp.asarray(tensor)
    mesh = ps.proc_mesh()
    p = mesh.devices.size
    x = _record_collective("allgather", x, p, pset=ps.process_set_id)
    if p == 1:
        # gather over one participant is identity — but callers are
        # promised a NEW tensor (frontend DLPack round-trips would
        # otherwise alias the input buffer; same contract as allreduce)
        return jnp.copy(x)
    # dim0 excluded from the descriptor: per-rank sizes are legitimate
    # for allgather and negotiated right below
    tname = name or f"allgather.{x.shape[1:]}.{x.dtype}"
    sdesc = stall.check(
        st, ps, f"allgather:{tname}:{tuple(x.shape[1:])}:{x.dtype}")
    sizes = _exchange_dim0_sizes(x.shape[0], mesh, st, ps)
    maxd = int(sizes.max())
    padded = (
        x
        if x.shape[0] == maxd
        else jnp.pad(x, [(0, maxd - x.shape[0])] + [(0, 0)] * (x.ndim - 1))
    )
    # padded.nbytes is negotiated (maxd), so the lane routing guard is
    # rank-consistent even with ragged per-rank dim0
    md = (None if padded.nbytes < _MULTIDEV_MIN_BYTES
          else _multidev_mesh_or_none(ps))
    if md is not None:
        stacked, flat_size = _stack_global_multidev(padded, md)
        out = _fetch(stall.dispatch(
            st, ps, _jitted("allgather_multidev", md, ()), (stacked,),
            desc=sdesc))
        gathered = out[:, :flat_size].reshape((p,) + padded.shape)
    else:
        stacked = _stack_global(padded, mesh)
        gathered = _fetch(stall.dispatch(
            st, ps, _jitted("allgather", mesh, ()), (stacked,),
            desc=sdesc))
    # gathered: (P, maxd, ...); trim each rank's block to its size.
    if all(int(s) == maxd for s in sizes):
        out = gathered.reshape((p * maxd,) + gathered.shape[2:])
    else:
        parts = [gathered[r, : int(sizes[r])] for r in range(p)]
        out = jnp.concatenate(parts, axis=0)
    return _post_collective("allgather",
                            stall.finish(st, ps, out, sdesc),
                            pset=ps.process_set_id)


def broadcast(tensor, *, root_rank: int = 0, process_set=None,
              name: Optional[str] = None):
    st, ps = _resolve_process_set(process_set)
    x = jnp.asarray(tensor)
    mesh = ps.proc_mesh()
    x = _record_collective("broadcast", x, mesh.devices.size,
                           pset=ps.process_set_id)
    if mesh.devices.size == 1:
        return jnp.copy(x)  # new-tensor contract (see allgather)
    # root_rank is a *global* rank (reference semantics); translate to
    # the set-relative index the proc-mesh axis uses.
    root_in_set = ps.rank_in_set(root_rank)
    if root_in_set < 0:
        raise ValueError(
            f"root_rank {root_rank} is not a member of process set "
            f"{ps.process_set_id} (ranks {ps.ranks})"
        )
    tname = name or f"broadcast.{x.shape}.{x.dtype}"
    sdesc = stall.check(
        st, ps,
        f"broadcast:{tname}:{tuple(x.shape)}:{x.dtype}:root{root_rank}")
    md = (None if x.nbytes < _MULTIDEV_MIN_BYTES
          else _multidev_mesh_or_none(ps))
    if md is not None:
        stacked, flat_size = _stack_global_multidev(x, md)
        out = _fetch(stall.dispatch(
            st, ps, _jitted("broadcast_multidev", md, (root_in_set,)),
            (stacked,), desc=sdesc))
        return _post_collective(
            "broadcast",
            stall.finish(st, ps, out[:flat_size].reshape(x.shape), sdesc),
            pset=ps.process_set_id)
    stacked = _stack_global(x, mesh)
    out = stall.dispatch(
        st, ps, _jitted("broadcast", mesh, (root_in_set,)), (stacked,),
        desc=sdesc)
    return _post_collective("broadcast",
                            stall.finish(st, ps, _fetch(out), sdesc),
                            pset=ps.process_set_id)


def alltoall(tensor, splits=None, *, process_set=None,
             name: Optional[str] = None):
    """Distribute dim-0 slices to every rank.

    Returns the received tensor when ``splits`` is None (equal splits),
    or ``(received_tensor, received_splits)`` when ``splits`` is given —
    matching the reference's return convention
    (horovod/torch/mpi_ops.py alltoall).

    ``splits[i]`` rows go to rank i.  Variable splits are handled by
    padding each chunk to the negotiated max and trimming after the
    wire exchange.
    """
    st, ps = _resolve_process_set(process_set)
    x = jnp.asarray(tensor)
    mesh = ps.proc_mesh()
    p = mesh.devices.size
    x = _record_collective("alltoall", x, p, pset=ps.process_set_id)
    return_splits = splits is not None
    if splits is None:
        if x.shape[0] % p:
            raise ValueError(
                f"alltoall dim0 {x.shape[0]} not divisible by size {p}"
            )
        splits = np.full((p,), x.shape[0] // p, np.int32)
    splits = np.asarray(splits, np.int32)
    if splits.shape != (p,) or int(splits.sum()) != x.shape[0]:
        raise ValueError("splits must be a (size,) vector summing to dim0")
    if p == 1:
        out = jnp.copy(x)  # new-tensor contract (see allgather)
        return (out, jnp.asarray(splits)) if return_splits else out
    tname = name or f"alltoall.{x.shape[1:]}.{x.dtype}"
    sdesc = stall.check(
        st, ps, f"alltoall:{tname}:{tuple(x.shape[1:])}:{x.dtype}")

    # Negotiate the split matrix: row r = rank r's send splits.
    split_matrix = np.asarray(
        allgather(jnp.asarray(splits)[None], process_set=ps)
    ).reshape(p, p)
    recv_splits = split_matrix[:, ps.rank_in_set(st.rank)]
    max_chunk = int(split_matrix.max())

    offsets = np.concatenate([[0], np.cumsum(splits)[:-1]])
    chunks = [
        jnp.pad(
            x[int(o) : int(o + s)],
            [(0, max_chunk - int(s))] + [(0, 0)] * (x.ndim - 1),
        )
        for o, s in zip(offsets, splits)
    ]
    send = jnp.stack(chunks)  # (P, max_chunk, ...)
    # send.nbytes derives from the negotiated max_chunk: rank-consistent
    md = (None if send.nbytes < _MULTIDEV_MIN_BYTES
          else _multidev_mesh_or_none(ps))
    if md is not None:
        stacked, inner = _stack_global_multidev_rows(send, p, md)
        got = _fetch(stall.dispatch(
            st, ps, _jitted("alltoall_multidev", md, ()), (stacked,),
            desc=sdesc))[0]
        out = got[:, :inner].reshape((p, max_chunk) + x.shape[1:])
    else:
        stacked = _stack_global(send, mesh)
        # local shard of the (P, P, max_chunk, ...) output:
        # (1, P, max_chunk, ...)
        out = _fetch(stall.dispatch(
            st, ps, _jitted("alltoall", mesh, ()), (stacked,),
            desc=sdesc))[0]
    parts = [out[r, : int(recv_splits[r])] for r in range(p)]
    result = stall.finish(st, ps, jnp.concatenate(parts, axis=0), sdesc)
    return (result, jnp.asarray(recv_splits)) if return_splits else result


def reducescatter(tensor, *, op=None, process_set=None,
                  name: Optional[str] = None):
    """Reduce across ranks, return this rank's dim-0 shard.

    Divisible dim 0 uses a true ``psum_scatter`` (each rank receives only
    its 1/P of the wire bytes).  Uneven dim 0 follows the reference rule
    — the first ``dim0 % size`` ranks receive one extra row — via the
    allreduce-then-slice fallback (XLA's scatter needs equal shards).
    """
    rop = normalize_op(op, None)
    st, ps = _resolve_process_set(process_set)
    x = jnp.asarray(tensor)
    p = ps.size
    x = _record_collective("reducescatter", x, p, pset=ps.process_set_id)
    if p == 1:
        return jnp.copy(x)  # new-tensor contract (see allgather)
    tname = name or f"reducescatter.{x.shape}.{x.dtype}"
    sdesc = stall.check(
        st, ps,
        f"reducescatter:{tname}:{tuple(x.shape)}:{x.dtype}:{rop.name}")
    if x.shape[0] % p == 0:
        # lane path: Sum/Average only (psum_scatter is a sum wire) and
        # float Average only (int AVERAGE has floor-div semantics the
        # flat kernel implements)
        md = (None if (x.nbytes < _MULTIDEV_MIN_BYTES
                       or rop not in (ReduceOp.SUM, ReduceOp.AVERAGE)
                       or (rop == ReduceOp.AVERAGE
                           and jnp.issubdtype(x.dtype, jnp.integer)))
              else _multidev_mesh_or_none(ps))
        if md is not None:
            q = x.shape[0] // p
            stacked, inner = _stack_global_multidev_rows(x, p, md)
            out = _fetch(stall.dispatch(
                st, ps, _jitted("reducescatter_multidev", md, (rop,)),
                (stacked,), desc=sdesc))
            return _post_collective(
                "reducescatter",
                stall.finish(
                    st, ps, out[0][:inner].reshape((q,) + x.shape[1:]),
                    sdesc),
                pset=ps.process_set_id)
        mesh = ps.proc_mesh()
        stacked = _stack_global(x, mesh)
        out = _fetch(stall.dispatch(
            st, ps, _jitted("reducescatter", mesh, (rop,)), (stacked,),
            desc=sdesc))[0]
        return _post_collective("reducescatter",
                                stall.finish(st, ps, out, sdesc),
                                pset=ps.process_set_id)
    reduced = allreduce(x, op=rop, process_set=ps)
    r = ps.rank_in_set(st.rank)
    base, extra = divmod(x.shape[0], p)
    start = r * base + min(r, extra)
    length = base + (1 if r < extra else 0)
    return reduced[start : start + length]


def barrier(*, process_set=None):
    """Block until every member reaches the barrier (parity: hvd.barrier)."""
    st, ps = _resolve_process_set(process_set)
    # the carrying allreduce below also counts itself — a barrier IS a
    # scalar allreduce on this data plane
    obs_metrics.op_counter("barrier").inc()
    if ps.size == 1:
        return
    out = allreduce(jnp.zeros((), jnp.int32), op=ReduceOp.SUM, process_set=ps)
    jax.block_until_ready(out)
