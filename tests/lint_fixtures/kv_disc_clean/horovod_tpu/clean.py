"""kv-discipline clean fixture: the disciplined wrapper patterns."""

from jax._src import distributed as _jd

from horovod_tpu.core import retry as core_retry
from horovod_tpu.core.retry import fenced_kv, resilient_kv


def wrapped_resilient():
    client = _jd.global_state.client
    if client is None:
        return None
    kv = resilient_kv(client, rank=0)
    kv.key_value_set("hvt/k", "v")
    return kv.blocking_key_value_get("hvt/k", 1000)


def wrapped_fenced_rebind():
    # the common rebind idiom: same name, now the wrapper
    client = _jd.global_state.client
    client = core_retry.fenced_kv(client, rank=0)
    client.key_value_set("hvt/k", "v")


def rebound_to_none():
    try:
        client = _jd.global_state.client
    except Exception:
        client = None
    client = fenced_kv(client, rank=0)
    return client


def calls_on_parameter(kv):
    # callers hand in an already-wrapped KV; not a raw client
    kv.key_value_set("hvt/k", "v")
    return kv.key_value_dir_get("hvt/")


class Holder:
    def __init__(self, client=None):
        if client is None:
            client = _jd.global_state.client
        # stored only after wrapping: no escape
        self._kv = fenced_kv(client, rank=0)

    def put(self):
        self._kv.key_value_set("hvt/k", "v")
