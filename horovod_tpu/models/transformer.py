"""Flagship model: GPT-style transformer with hybrid dp/tp/pp/sp/ep
sharding.

The reference frames distributed training as "wrap your optimizer"
around a data-parallel core (horovod/torch/optimizer.py
``DistributedOptimizer``); its model zoo is examples-level
(examples/pytorch/pytorch_synthetic_benchmark.py).  Here the flagship
is TPU-first: a functional (pure-pytree) decoder-only transformer whose
*single* training step jits over a ``MeshLayout`` exercising all five
parallelism axes at once:

* **dp** — batch sharded; gradient reduction falls out of shard_map's
  transpose rules (replicated-param cotangents psum over data axes).
* **tp** — Megatron column→row sharded attention/MLP projections.
* **sp** — sequence sharded between blocks.  Two modes:
  ``megatron_sp`` (sp shares the tp group: all_gather in, psum_scatter
  out — exact, zero extra devices) and ``ring`` (dedicated sp axis,
  ring attention via ppermute for long context).
* **pp** — blocks stacked per stage, GPipe microbatch schedule
  (parallel/pipeline.py) over the pp axis.
* **ep** — optional Switch-MoE MLPs with experts sharded over the ep
  axis (defaults to sharing dp) and all_to_all token dispatch.

Everything is static-shaped, scan-based, and bf16-friendly so XLA can
tile matmuls onto the MXU and overlap ICI collectives with compute.

Gradient-reduction correctness is *structural*: params enter shard_map
with their true ``PartitionSpec`` (``param_specs``), so jax's
varying-axis tracking inserts exactly the right psums when
transposing — there is no hand-maintained per-leaf reduction table to
drift out of sync.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import MeshLayout
from ..parallel.moe import expert_parallel_moe
from ..parallel.pipeline import pipeline_apply
from ..parallel.ring import ring_attention
from ..parallel.ulysses import ulysses_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 8
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: Any = jnp.bfloat16
    attn_mode: str = "megatron_sp"  # "megatron_sp" | "ring" | "ulysses"
    n_experts: int = 0  # 0 → dense MLP in every block
    capacity_factor: float = 2.0
    aux_loss_weight: float = 0.01
    num_microbatches: int = 0  # 0 → 2 * pp

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_params(cfg: TransformerConfig, rng: jax.Array) -> Dict[str, Any]:
    """Global (unsharded) parameter pytree; blocks stacked on a leading
    layer dim so they can be pp-sharded and lax.scan'd."""
    d, h, dh, f, L = (cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff,
                      cfg.n_layers)
    ks = jax.random.split(rng, 8)

    def norm(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(cfg.dtype)

    blocks: Dict[str, Any] = {
        "ln1": jnp.ones((L, d), cfg.dtype),
        # [L, D, 3, H*Dh]: q/k/v on their own dim so tp-sharding the
        # last dim splits *heads*, never mixes q/k/v columns.
        "wqkv": norm(ks[0], (L, d, 3, h * dh), d),
        "wo": norm(ks[1], (L, h * dh, d), h * dh),
        "ln2": jnp.ones((L, d), cfg.dtype),
    }
    if cfg.n_experts:
        e = cfg.n_experts
        blocks["gate"] = (jax.random.normal(ks[2], (L, d, e), jnp.float32)
                          * 0.02)
        blocks["we1"] = norm(ks[3], (L, e, d, f), d)
        blocks["we2"] = norm(ks[4], (L, e, f, d), f)
    else:
        blocks["w1"] = norm(ks[3], (L, d, f), d)
        blocks["b1"] = jnp.zeros((L, f), cfg.dtype)
        blocks["w2"] = norm(ks[4], (L, f, d), f)
        blocks["b2"] = jnp.zeros((L, d), cfg.dtype)

    return {
        "embed": (jax.random.normal(ks[5], (cfg.vocab_size, d), jnp.float32)
                  * 0.02).astype(cfg.dtype),
        "pos": (jax.random.normal(ks[6], (cfg.max_seq, d), jnp.float32)
                * 0.02).astype(cfg.dtype),
        "blocks": blocks,
        "ln_f": jnp.ones((d,), cfg.dtype),
    }


def param_specs(cfg: TransformerConfig, layout: MeshLayout) -> Dict[str, Any]:
    """PartitionSpecs matching init_params: blocks pp-sharded on the
    layer dim, projections tp-sharded Megatron-style, experts
    ep-sharded.  These specs double as shard_map in_specs — which is
    what makes gradient psums automatic and provably aligned with the
    layout."""
    tp, pp, ep = layout.tp, layout.pp, layout.ep
    blocks: Dict[str, Any] = {
        "ln1": P(pp, None),
        "wqkv": P(pp, None, None, tp),
        "wo": P(pp, tp, None),
        "ln2": P(pp, None),
    }
    if cfg.n_experts:
        blocks["gate"] = P(pp, None, None)
        blocks["we1"] = P(pp, ep, None, None)
        blocks["we2"] = P(pp, ep, None, None)
    else:
        blocks["w1"] = P(pp, None, tp)
        blocks["b1"] = P(pp, tp)
        blocks["w2"] = P(pp, tp, None)
        blocks["b2"] = P(pp, None)
    return {
        "embed": P(),
        "pos": P(),
        "blocks": blocks,
        "ln_f": P(),
    }


# ---------------------------------------------------------------------------
# local (inside-shard_map) forward
# ---------------------------------------------------------------------------

def _rms_norm(x, w):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * scale).astype(x.dtype) * w


def _dense_causal_attention(q, k, v):
    # q,k,v: [B, T, h, Dh] — full sequence, local head subset.
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _attention(cfg: TransformerConfig, p, x, axes) -> jax.Array:
    """One attention sublayer on a seq-sharded activation
    ``[B_mb, T_local, D]``; returns same shape."""
    sp_ax, tp_ax = axes["sp"], axes["tp"]
    h_local = cfg.n_heads // lax.axis_size(tp_ax)
    dh = cfg.head_dim
    xn = _rms_norm(x, p["ln1"])

    if cfg.attn_mode == "megatron_sp":
        # sp == tp group: gather sequence in, scatter it back out.
        xg = lax.all_gather(xn, tp_ax, axis=1, tiled=True)  # [B, T, D]
        qkv = jnp.einsum("btd,dcf->btcf", xg, p["wqkv"])
        q = qkv[:, :, 0].reshape(*qkv.shape[:2], h_local, dh)
        k = qkv[:, :, 1].reshape(*qkv.shape[:2], h_local, dh)
        v = qkv[:, :, 2].reshape(*qkv.shape[:2], h_local, dh)
        o = _dense_causal_attention(q, k, v)
        o = o.reshape(*o.shape[:2], h_local * dh)
        y = jnp.einsum("btf,fd->btd", o, p["wo"])  # partial over tp
        y = lax.psum_scatter(y, tp_ax, scatter_dimension=1, tiled=True)
    else:
        # dedicated sp axis: projections tp-parallel, attention sp-parallel.
        qkv = jnp.einsum("btd,dcf->btcf", xn, p["wqkv"])
        q = qkv[:, :, 0].reshape(*qkv.shape[:2], h_local, dh)
        k = qkv[:, :, 1].reshape(*qkv.shape[:2], h_local, dh)
        v = qkv[:, :, 2].reshape(*qkv.shape[:2], h_local, dh)
        if cfg.attn_mode == "ring":
            o = ring_attention(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), sp_ax, causal=True,
            ).transpose(0, 2, 1, 3)
        elif cfg.attn_mode == "ulysses":
            o = ulysses_attention(q, k, v, sp_ax, causal=True)
        else:
            raise ValueError(f"unknown attn_mode {cfg.attn_mode!r}")
        o = o.reshape(*o.shape[:2], h_local * dh)
        y = jnp.einsum("btf,fd->btd", o, p["wo"])
        y = lax.psum(y, tp_ax)
    return x + y.astype(x.dtype)


def _mlp(cfg: TransformerConfig, p, x, axes) -> jax.Array:
    """Dense (tp column→row) or Switch-MoE (ep all_to_all) MLP sublayer
    on ``[B_mb, T_local, D]``; returns (out, aux_loss)."""
    tp_ax, ep_ax = axes["tp"], axes["ep"]
    xn = _rms_norm(x, p["ln2"])

    if cfg.n_experts:
        b, t, d = xn.shape
        tokens = xn.reshape(b * t, d)
        ep = lax.axis_size(ep_ax)
        e_local = cfg.n_experts // ep

        def expert_fn(ep_params, tok):
            w1, w2 = ep_params
            return jnp.einsum(
                "cf,fd->cd",
                jax.nn.gelu(jnp.einsum("cd,df->cf", tok, w1)), w2,
            )

        y, aux = expert_parallel_moe(
            tokens, p["gate"], (p["we1"], p["we2"]), expert_fn, ep_ax,
            num_experts=cfg.n_experts,
            capacity_factor=cfg.capacity_factor,
        )
        return x + y.reshape(b, t, d).astype(x.dtype), aux

    if cfg.attn_mode == "megatron_sp":
        xg = lax.all_gather(xn, tp_ax, axis=1, tiled=True)
        hmid = jax.nn.gelu(jnp.einsum("btd,df->btf", xg, p["w1"]) + p["b1"])
        y = jnp.einsum("btf,fd->btd", hmid, p["w2"])
        y = lax.psum_scatter(y, tp_ax, scatter_dimension=1, tiled=True)
        y = y + p["b2"]
    else:
        hmid = jax.nn.gelu(jnp.einsum("btd,df->btf", xn, p["w1"]) + p["b1"])
        y = jnp.einsum("btf,fd->btd", hmid, p["w2"])
        y = lax.psum(y, tp_ax) + p["b2"]
    return x + y.astype(x.dtype), jnp.zeros((), jnp.float32)


def _block(cfg, layer_params, x, axes):
    """One transformer block; x: [B_mb, T_local, D] → (same, aux)."""
    x = _attention(cfg, layer_params, x, axes)
    x, aux = _mlp(cfg, layer_params, x, axes)
    return x, aux


def forward_local(
    cfg: TransformerConfig,
    params: Dict[str, Any],
    tokens: jax.Array,
    axes: Dict[str, str],
) -> Tuple[jax.Array, jax.Array]:
    """Full decoder forward INSIDE shard_map.

    Args:
      params: the local shards (blocks' leading layer dim is this pp
        stage's slice; tp/ep dims are local slices).
      tokens: ``[B_local, T]`` — batch dp-sharded, sequence full.
      axes: logical→physical axis names (MeshLayout.logical_to_physical).

    Returns:
      (loss, aux_loss): scalars, fully psum'd (replicated).
    """
    sp_ax, pp_ax, dp_ax = axes["sp"], axes["pp"], axes["dp"]
    sp_size = lax.axis_size(sp_ax)
    sp_idx = lax.axis_index(sp_ax)
    pp_size = lax.axis_size(pp_ax)

    b_local, t_full = tokens.shape
    t_in = t_full - 1
    if t_in % sp_size:
        raise ValueError(f"seq len {t_in} not divisible by sp={sp_size}")
    t_local = t_in // sp_size

    inp, labels = tokens[:, :-1], tokens[:, 1:]

    # Embed, then take this sp member's sequence slice.
    x = params["embed"][inp] + params["pos"][:t_in][None]
    x = lax.dynamic_slice_in_dim(x, sp_idx * t_local, t_local, axis=1)
    labels_loc = lax.dynamic_slice_in_dim(
        labels, sp_idx * t_local, t_local, axis=1
    )

    # Microbatch for the pipeline.
    n_micro = cfg.num_microbatches or max(1, 2 * pp_size)
    if b_local % n_micro:
        raise ValueError(
            f"local batch {b_local} not divisible by {n_micro} microbatches"
        )
    mb = x.reshape(n_micro, b_local // n_micro, t_local, cfg.d_model)

    def stage_fn(stage_params, xmb):
        # scan over this stage's layers (leading dim of each leaf)
        def layer(carry, lp):
            y, aux = _block(cfg, lp, carry, axes)
            return y, aux
        y, auxs = lax.scan(layer, xmb, stage_params)
        return y, auxs.sum()

    # GPipe over pp (runs fine at pp=1 too); aux accumulates across
    # stages/microbatches inside the schedule (bubble ticks masked).
    out, aux_total = pipeline_apply(
        stage_fn, params["blocks"], mb, pp_ax, with_aux=True
    )
    x = out.reshape(b_local, t_local, cfg.d_model)
    # Mean MoE aux across microbatches and routing groups (dp×sp).
    # The extra pmean over tp is mathematically the identity (every tp
    # member computes the same value after the row-parallel psums) but
    # makes that invariance explicit to shard_map's varying-axis
    # checker, which over-approximates through the pipeline scan.
    aux_acc = lax.pmean(aux_total / n_micro, (dp_ax, sp_ax))
    aux_acc = lax.pmean(aux_acc, axes["tp"])

    x = _rms_norm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", x, params["embed"])  # tied head

    logits32 = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits32, axis=-1)
    nll = -jnp.take_along_axis(logp, labels_loc[..., None], axis=-1)[..., 0]
    local_sum = nll.sum()
    total = lax.psum(local_sum, (dp_ax, sp_ax))
    denom = b_local * t_in * lax.axis_size(dp_ax)
    loss = total / denom
    # Identity pmean: see aux_acc comment above.
    loss = lax.pmean(loss, axes["tp"])
    return loss, aux_acc


# ---------------------------------------------------------------------------
# public: jitted hybrid train/eval step builders
# ---------------------------------------------------------------------------

def make_loss_fn(cfg: TransformerConfig, layout: MeshLayout):
    """Returns ``loss(params, tokens) -> scalar`` where the shard_map is
    inside, so ``jax.grad`` of it yields correctly-reduced global
    gradients (see module docstring)."""
    axes = dict(layout.logical_to_physical)
    specs = param_specs(cfg, layout)
    dp_ax = layout.dp

    if cfg.attn_mode == "megatron_sp" and axes["sp"] != axes["tp"]:
        raise ValueError(
            "attn_mode='megatron_sp' requires sp to share the tp group "
            "(make_layout without a dedicated sp axis); with a dedicated "
            "sp axis use attn_mode='ring' or 'ulysses'"
        )

    def loss_fn(params, tokens):
        def body(params, tokens):
            loss, aux = forward_local(cfg, params, tokens, axes)
            return loss + cfg.aux_loss_weight * aux

        return jax.shard_map(
            body,
            mesh=layout.mesh,
            in_specs=(specs, P(dp_ax, None)),
            out_specs=P(),
        )(params, tokens)

    return loss_fn


def make_train_step(cfg: TransformerConfig, layout: MeshLayout, optimizer):
    """Jitted full hybrid-parallel train step:
    ``step(params, opt_state, tokens) -> (params, opt_state, loss)``."""
    loss_fn = make_loss_fn(cfg, layout)

    import optax

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
