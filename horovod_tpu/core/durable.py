"""Crash-consistent durable snapshot plane: atomic commits, integrity
manifests, retention, a background writer, and the restore quorum.

Every exactly-once guarantee upstream (elastic commits, the data
cursor, drain accounting) bottoms out in a file reaching disk intact.
A host dying mid-write, a torn rename on ENOSPC, or silent media
corruption defeats them all after the fact — and the PR 4 divergence
audit only catches the damage once it has already cost the run.  This
module is the storage leg of "survive anything":

**Commit protocol** (:func:`write_snapshot`): each commit is a
directory ``commits/c_{seq:010d}`` whose payload files are written
tmp → fsync(file) → rename → fsync(dir); a ``MANIFEST.json`` recording
each file's **intended** sha256 + byte size is committed LAST through
the same discipline, so its rename is the commit point.  A crash at
any earlier instant leaves a directory without a (valid) manifest —
by construction detectable, never silently loadable.  The newest
``HVTPU_CKPT_KEEP`` committed snapshots are retained; older commits
and dead uncommitted attempts are GC'd.

**Verification** (:func:`verify_snapshot` / :func:`latest_verified`):
a reader re-hashes payload files against the manifest; torn writes and
bit flips (real, or injected via the ``ckpt.*`` fault sites) fail
verification and the snapshot is skipped — restore falls back to the
previous retained commit.

**Background writer** (:class:`DurableWriter`): the caller snapshots
to memory at the commit boundary (cheap) and the disk write runs on a
bounded-queue daemon thread, off the step critical path.  Write errors
surface on the next ``submit``/``flush``; the drain path
(core/preempt.py) and the elastic reset path (elastic/worker.py)
quiesce the writer before their ``os._exit``, and an atexit hook
covers ordinary interpreter shutdown.

**Restore quorum** (:func:`restore_quorum`): after a restart each rank
publishes its highest locally-verified commit over the coordination KV
(wrapped in :class:`~horovod_tpu.core.retry.ResilientKV`), and the
agreed restore point is the MINIMUM over ranks — the highest commit
durable on **all** of them.  A straggler whose newest snapshot is torn
delays the pick to an older common commit; it can never diverge it,
because every rank computes the same min over the same votes.

Chaos surface: the ``ckpt.write`` / ``ckpt.fsync`` / ``ckpt.rename``
fault sites (core/faults.py) fire inside :func:`atomic_write` with
``torn`` / ``bitflip`` / ``drop`` / ``error`` / ``kill`` actions, so
the whole path — damage, detection, fallback, quorum — is exercised
end-to-end by the 2-process chaos tests and the ``checkpoint-storm``
sim scenario at 256-1024 virtual ranks.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import logging
import os
import queue
import re
import shutil
import threading
from typing import Callable, Dict, List, Optional

from . import clock, faults
from ..obs import flight
from ..obs import metrics as obs_metrics

logger = logging.getLogger("horovod_tpu")

__all__ = [
    "MANIFEST", "atomic_write", "write_snapshot", "verify_snapshot",
    "list_snapshots", "latest_verified", "read_snapshot", "gc_snapshots",
    "snapshot_path", "restore_quorum", "DurableWriter", "shared_writer",
    "quiesce_writers",
]

#: The commit marker: a snapshot directory is committed iff this file
#: exists and parses.  Written LAST — its atomic rename IS the commit.
MANIFEST = "MANIFEST.json"

_SNAP_RE = re.compile(r"^c_(\d{10})$")

_M_COMMIT_S = obs_metrics.histogram(
    "hvtpu_ckpt_commit_seconds",
    "durable snapshot commit latency (payload writes + fsyncs + "
    "manifest rename), per write_snapshot call")
_M_BYTES = obs_metrics.counter(
    "hvtpu_ckpt_bytes_written_total",
    "bytes physically written by the durable commit protocol "
    "(post-damage: a torn write counts what actually hit disk)")
_M_VERIFY_FAIL = obs_metrics.counter(
    "hvtpu_ckpt_verify_failures_total",
    "snapshots rejected by manifest verification (missing/unparsable "
    "manifest, size mismatch, or sha256 mismatch)")
_M_QUORUM_ROUNDS = obs_metrics.counter(
    "hvtpu_ckpt_restore_quorum_rounds_total",
    "restore-time cross-rank agreement rounds run over the "
    "coordination KV")


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def _keep() -> int:
    """HVTPU_CKPT_KEEP: retained last-good snapshots (min 1)."""
    try:
        return max(1, int(os.environ.get("HVTPU_CKPT_KEEP", "2") or 2))
    except ValueError:
        return 2


def _fsync_enabled() -> bool:
    """HVTPU_CKPT_FSYNC: fsync discipline on payload/manifest/dir
    writes.  On by default; tests and the simulator turn it off (a
    tmpfs fsync is pure syscall overhead and tier-1 runs thousands)."""
    return os.environ.get("HVTPU_CKPT_FSYNC", "1") not in ("0", "false")


def _async_enabled() -> bool:
    """HVTPU_CKPT_ASYNC: run durable writes on the background writer
    (snapshot-to-memory at the boundary, disk off the critical path)."""
    return os.environ.get("HVTPU_CKPT_ASYNC", "1") not in ("0", "false")


def _queue_depth() -> int:
    """HVTPU_CKPT_QUEUE: background-writer queue bound; a full queue
    blocks the submitter (natural backpressure, bounded memory)."""
    try:
        return max(1, int(os.environ.get("HVTPU_CKPT_QUEUE", "2") or 2))
    except ValueError:
        return 2


def _quorum_timeout_s() -> float:
    """HVTPU_CKPT_QUORUM_TIMEOUT_S: per-peer wait for restore-quorum
    votes before the caller falls back to its local best."""
    try:
        return float(os.environ.get("HVTPU_CKPT_QUORUM_TIMEOUT_S",
                                    "600") or 600)
    except ValueError:
        return 600.0


# ---------------------------------------------------------------------------
# the atomic write primitive (all three fault sites live here)
# ---------------------------------------------------------------------------

def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes, *,
                 fsync: Optional[bool] = None,
                 detail: Optional[str] = None) -> int:
    """Write ``data`` to ``path`` crash-atomically: tmp file in the
    same directory → fsync(file) → rename → fsync(directory).  Returns
    the byte count physically written.

    The three storage fault sites fire here when armed:
    ``ckpt.write`` before the payload hits the tmp file (``torn``
    truncates it mid-file, ``bitflip`` flips one bit, ``drop`` elides
    the write), ``ckpt.fsync`` before the file fsync (``drop``/damage
    actions elide it), ``ckpt.rename`` before the promote (eliding it
    leaves an uncommitted tmp — a torn commit).  ``error`` raises
    OSError-shaped :class:`~.faults.InjectedFault`; ``kill`` dies
    mid-commit, which is the whole point.
    """
    detail = detail or os.path.basename(path)
    payload = data
    if faults.ACTIVE:
        damage = faults.inject_storage("ckpt.write", detail=detail)
        if damage == "torn":
            payload = data[: len(data) // 2]
        elif damage == "bitflip":
            buf = bytearray(data)
            if buf:
                buf[len(buf) // 2] ^= 0x01
            payload = bytes(buf)
        elif damage == "drop":
            return 0
    do_fsync = _fsync_enabled() if fsync is None else fsync
    dirname = os.path.dirname(path) or "."
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        if do_fsync:
            skip = (faults.inject_storage("ckpt.fsync", detail=detail)
                    if faults.ACTIVE else None)
            if skip is None:
                os.fsync(f.fileno())
    if faults.ACTIVE:
        if faults.inject_storage("ckpt.rename", detail=detail) is not None:
            # rename elided: the write never commits (torn commit) —
            # leave the tmp behind exactly as a crash would
            _M_BYTES.inc(len(payload))
            return len(payload)
    os.replace(tmp, path)
    if do_fsync:
        # the rename itself must be durable: fsync the directory
        try:
            _fsync_path(dirname)
        except OSError:  # pragma: no cover - exotic filesystems
            logger.warning("durable: directory fsync failed for %s",
                           dirname, exc_info=True)
    _M_BYTES.inc(len(payload))
    return len(payload)


# ---------------------------------------------------------------------------
# snapshot commits
# ---------------------------------------------------------------------------

def snapshot_path(root: str, seq: int) -> str:
    return os.path.join(root, "commits", f"c_{seq:010d}")


def list_snapshots(root: str) -> List[int]:
    """All snapshot seqs under ``root`` (committed or not), sorted."""
    d = os.path.join(root, "commits")
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for n in names:
        m = _SNAP_RE.match(n)
        if m:
            out.append(int(m.group(1)))
    out.sort()
    return out


def _committed(path: str) -> Optional[dict]:
    """The parsed manifest when ``path`` holds a committed snapshot
    (manifest present and parsable), else None.  Cheap — no hashing."""
    try:
        with open(os.path.join(path, MANIFEST), "rb") as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def write_snapshot(root: str, seq: int, files: Dict[str, bytes], *,
                   fsync: Optional[bool] = None,
                   keep: Optional[int] = None,
                   meta: Optional[dict] = None) -> str:
    """Commit ``files`` (name → bytes) as snapshot ``seq`` under
    ``root`` and GC beyond the retention window.  The manifest records
    each file's INTENDED hash/size and is written last, so any damage
    to the payload en route (torn write, bit flip, crash) is caught by
    :func:`verify_snapshot` instead of being silently loaded."""
    t0 = clock.monotonic()
    d = snapshot_path(root, seq)
    if os.path.isdir(d):
        # a leftover attempt at this seq (crash before commit, or a
        # relaunched rank redoing the boundary): start clean
        shutil.rmtree(d, ignore_errors=True)
    os.makedirs(d, exist_ok=True)
    manifest: Dict[str, object] = {"seq": seq, "files": {}}
    if meta is not None:
        manifest["meta"] = meta
    for name in sorted(files):
        data = files[name]
        manifest["files"][name] = {
            "sha256": hashlib.sha256(data).hexdigest(),
            "bytes": len(data),
        }
        atomic_write(os.path.join(d, name), data, fsync=fsync,
                     detail=f"{name}@c{seq}")
    atomic_write(
        os.path.join(d, MANIFEST),
        json.dumps(manifest, sort_keys=True).encode(),
        fsync=fsync, detail=f"manifest@c{seq}")
    _M_COMMIT_S.observe(clock.monotonic() - t0)
    if flight.ACTIVE:
        flight.note("durable_commit", seq=seq,
                    files=len(files),
                    commit_s=round(clock.monotonic() - t0, 6))
    gc_snapshots(root, keep=keep)
    return d


def verify_snapshot(path: str) -> bool:
    """Full integrity check: manifest parses AND every payload file
    matches its recorded byte size and sha256.  Counts a
    ``hvtpu_ckpt_verify_failures_total`` on rejection."""
    manifest = _committed(path)
    if manifest is None:
        _M_VERIFY_FAIL.inc()
        return False
    for name, rec in manifest.get("files", {}).items():
        try:
            with open(os.path.join(path, name), "rb") as f:
                data = f.read()
        except OSError:
            _M_VERIFY_FAIL.inc()
            return False
        if (len(data) != rec.get("bytes")
                or hashlib.sha256(data).hexdigest() != rec.get("sha256")):
            logger.warning(
                "durable: snapshot %s rejected — %s fails manifest "
                "verification (torn or corrupt)", path, name)
            _M_VERIFY_FAIL.inc()
            return False
    return True


def note_verify_failure() -> None:
    """Count an integrity rejection detected OUTSIDE this module (the
    sharded checkpointer verifies its own piece manifests) in the same
    ``hvtpu_ckpt_verify_failures_total`` family."""
    _M_VERIFY_FAIL.inc()


def latest_verified(root: str) -> Optional[int]:
    """Highest seq under ``root`` that passes full verification —
    walking DOWN through damaged/torn commits to the last good one."""
    for seq in reversed(list_snapshots(root)):
        if verify_snapshot(snapshot_path(root, seq)):
            return seq
    return None


def read_snapshot(root: str, seq: int) -> Dict[str, bytes]:
    """Payload files of committed snapshot ``seq`` (name → bytes)."""
    d = snapshot_path(root, seq)
    manifest = _committed(d)
    if manifest is None:
        raise FileNotFoundError(
            f"no committed snapshot c_{seq:010d} under {root!r}")
    out = {}
    for name in manifest.get("files", {}):
        with open(os.path.join(d, name), "rb") as f:
            out[name] = f.read()
    return out


def gc_snapshots(root: str, keep: Optional[int] = None) -> None:
    """Retention: keep the newest ``keep`` COMMITTED snapshots plus
    any seq newer than the newest commit (an in-flight write); drop
    older commits and dead uncommitted leftovers."""
    keep = _keep() if keep is None else max(1, int(keep))
    seqs = list_snapshots(root)
    committed = [s for s in seqs
                 if _committed(snapshot_path(root, s)) is not None]
    retain = set(committed[-keep:])
    newest = committed[-1] if committed else -1
    for s in seqs:
        if s in retain or s > newest:
            continue
        shutil.rmtree(snapshot_path(root, s), ignore_errors=True)


# ---------------------------------------------------------------------------
# restore-time cross-rank agreement
# ---------------------------------------------------------------------------

def restore_quorum(kv, *, rank: int, size: int, local_best: Optional[int],
                   namespace: str,
                   timeout_s: Optional[float] = None) -> Optional[int]:
    """Agree on the highest commit durable on EVERY rank.

    Each rank publishes its highest locally-verified seq (−1 when it
    has none) under ``namespace`` and blocking-reads all votes; the
    agreed restore point is ``min(votes)``, or None when any rank has
    nothing durable.  Deterministic in the votes, so every rank that
    completes the round picks the same seq — a straggler's stale or
    torn snapshot can lower the pick, never diverge it.

    ``kv`` is any coordination KV exposing ``key_value_set`` /
    ``blocking_key_value_get`` (production wraps the JAX coordination
    client in :class:`~horovod_tpu.core.retry.ResilientKV`; the fabric
    simulator passes its virtual client).  ``namespace`` must be
    unique per restore attempt (callers scope it by generation and a
    per-process round counter) so stale votes cannot bleed across
    rounds.  Timeouts propagate to the caller, which falls back to its
    local best — safe wherever a rank-0 broadcast carries the final
    pick, and the sim asserts the full-quorum path.
    """
    _M_QUORUM_ROUNDS.inc()
    if hasattr(kv, "add_journal_prefix"):
        # Quorum votes are the canonical "history a fresh coordinator
        # cannot recompute" (core/journal.py): journal this rank's
        # vote so a coordinator-loss relaunch can replay it.
        kv.add_journal_prefix(f"{namespace}/")
    vote = -1 if local_best is None else int(local_best)
    kv.key_value_set(f"{namespace}/vote/{rank}", str(vote))
    timeout_ms = int((_quorum_timeout_s() if timeout_s is None
                      else timeout_s) * 1000)
    agreed = vote
    for peer in range(size):
        if peer == rank:
            continue
        v = int(kv.blocking_key_value_get(
            f"{namespace}/vote/{peer}", timeout_ms))
        agreed = min(agreed, v)
    return None if agreed < 0 else agreed


# ---------------------------------------------------------------------------
# the background durable writer
# ---------------------------------------------------------------------------

_STOP = object()


class DurableWriter:
    """Bounded-queue daemon thread running durable writes off the step
    critical path.  ``submit`` blocks when the queue is full (bounded
    memory: at most HVTPU_CKPT_QUEUE snapshots in flight); a write
    error is captured and re-raised on the NEXT submit/flush — the
    same surfacing contract as the async Checkpointer's ``wait()``."""

    def __init__(self, name: str = "hvtpu-ckpt-writer",
                 maxsize: Optional[int] = None):
        self._name = name
        self._q: "queue.Queue" = queue.Queue(
            _queue_depth() if maxsize is None else maxsize)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None  # hvtpulint: guarded-by(_lock)
        self._error: Optional[BaseException] = None  # hvtpulint: guarded-by(_lock)
        self._closed = False  # hvtpulint: guarded-by(_lock)

    def _ensure_thread(self) -> None:  # hvtpulint: requires(_lock)
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is _STOP:
                    return
                item()
            except BaseException as e:  # noqa: BLE001 — surfaced later
                with self._lock:
                    self._error = e
                logger.error("durable writer: background write failed",
                             exc_info=True)
            finally:
                self._q.task_done()

    def _raise_pending_locked(self) -> None:  # hvtpulint: requires(_lock)
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                "durable background write failed") from err

    def submit(self, fn: Callable[[], None]) -> None:
        """Queue one write closure; blocks while the queue is full."""
        with self._lock:
            self._raise_pending_locked()
            if self._closed:
                raise RuntimeError("durable writer is closed")
            self._ensure_thread()
        self._q.put(fn)

    def flush(self) -> None:
        """Block until every queued write completed; re-raise a
        captured write error."""
        self._q.join()
        with self._lock:
            self._raise_pending_locked()

    def close(self) -> None:
        """Flush, then stop the thread.  Idempotent; errors from the
        final writes still surface."""
        with self._lock:
            if self._closed:
                thread = None
            else:
                self._closed = True
                thread = self._thread
        self._q.join()
        if thread is not None and thread.is_alive():
            self._q.put(_STOP)
            thread.join(timeout=30)
        with self._lock:
            self._raise_pending_locked()


_shared_lock = threading.Lock()
_shared: Optional[DurableWriter] = None  # guarded by _shared_lock
# (module-level: the thread-safety pass cannot track it; shared_writer/
# quiesce_writers are the only mutators and both take _shared_lock)


def shared_writer() -> DurableWriter:
    """The process-wide writer elastic state saves ride.  Lazily
    created; re-created after a quiesce (a relaunched incarnation in
    the same process gets a fresh thread)."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = DurableWriter()
        return _shared


def quiesce_writers() -> None:
    """Flush and stop the shared writer.  Exception-safe by contract —
    called from the drain path (core/preempt.py) before ``os._exit(79)``,
    from the elastic reset path (elastic/worker.py) before
    ``os._exit(73)``, and at interpreter exit; none of those may blow
    up on a write error, so it logs instead of raising."""
    global _shared
    with _shared_lock:
        w, _shared = _shared, None
    if w is None:
        return
    try:
        w.close()
    except BaseException:  # noqa: BLE001 — exit paths must not raise
        logger.error("durable writer: error while quiescing",
                     exc_info=True)


atexit.register(quiesce_writers)
