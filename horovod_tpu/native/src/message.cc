#include "message.h"

namespace hvt {

// Parity: message.cc Request::SerializeToString (FlatBuffers there).
static void WriteEntry(Writer& w, const Entry& e) {
  w.u64(e.seq);
  w.str(e.name);
  w.u8(static_cast<uint8_t>(e.type));
  w.u8(static_cast<uint8_t>(e.red_op));
  w.u8(static_cast<uint8_t>(e.dtype));
  w.u8(static_cast<uint8_t>(e.shape.size()));
  for (int64_t d : e.shape) w.i64(d);
  w.i32(e.process_set_id);
  w.i64(e.group_id);
  w.i32(e.root_rank);
}

static Entry ReadEntry(Reader& r) {
  Entry e;
  e.seq = r.u64();
  e.name = r.str();
  e.type = static_cast<OpType>(r.u8());
  e.red_op = static_cast<RedOp>(r.u8());
  e.dtype = static_cast<DataType>(r.u8());
  uint8_t ndim = r.u8();
  e.shape.resize(ndim);
  for (uint8_t i = 0; i < ndim; ++i) e.shape[i] = r.i64();
  e.process_set_id = r.i32();
  e.group_id = r.i64();
  e.root_rank = r.i32();
  return e;
}

std::vector<uint64_t> PackBits(const std::vector<uint32_t>& bits) {
  std::vector<uint64_t> words;
  for (uint32_t b : bits) {
    size_t w = b >> 6;
    if (words.size() <= w) words.resize(w + 1, 0);
    words[w] |= (uint64_t(1) << (b & 63));
  }
  return words;
}

std::vector<uint32_t> UnpackBits(const std::vector<uint64_t>& words) {
  std::vector<uint32_t> bits;
  for (size_t w = 0; w < words.size(); ++w) {
    uint64_t word = words[w];
    while (word) {
      int o = __builtin_ctzll(word);
      bits.push_back(static_cast<uint32_t>((w << 6) + o));
      word &= word - 1;
    }
  }
  return bits;
}

uint64_t Fnv1a64(const uint8_t* data, size_t n) {
  uint64_t h = 0xCBF29CE484222325ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= data[i];
    h *= 0x100000001B3ull;
  }
  return h;
}

std::vector<uint8_t> SerializeRequestList(const RequestList& rl) {
  Writer w;
  w.u32(kRequestMagic);
  w.u32(kWireVersion);
  w.i32(rl.rank);
  w.u8(rl.joined ? 1 : 0);
  w.u8(rl.shutdown ? 1 : 0);
  w.u8((rl.cache_bypass ? 1 : 0) | (rl.cache_resync ? 2 : 0) |
       (rl.predicted ? 4 : 0));
  w.u32(rl.burst_id);
  w.u32(rl.burst_len);
  w.u32(static_cast<uint32_t>(rl.cache_bits.size()));
  for (uint64_t word : rl.cache_bits) w.u64(word);
  w.u32(static_cast<uint32_t>(rl.cache_hits.size()));
  for (uint32_t b : rl.cache_hits) w.u32(b);
  w.u32(static_cast<uint32_t>(rl.requests.size()));
  for (const Request& rq : rl.requests) {
    w.i32(rq.rank);
    w.u8(rq.cached ? 1 : 0);
    w.u32(rq.cache_bit);
    WriteEntry(w, rq.entry);
  }
  return std::move(w.buf);
}

RequestList ParseRequestList(const uint8_t* data, size_t len) {
  Reader r(data, len);
  if (r.u32() != kRequestMagic) throw std::runtime_error("bad request magic");
  if (r.u32() != kWireVersion) throw std::runtime_error("bad wire version");
  RequestList rl;
  rl.rank = r.i32();
  rl.joined = r.u8() != 0;
  rl.shutdown = r.u8() != 0;
  uint8_t flags = r.u8();
  rl.cache_bypass = (flags & 1) != 0;
  rl.cache_resync = (flags & 2) != 0;
  rl.predicted = (flags & 4) != 0;
  rl.burst_id = r.u32();
  rl.burst_len = r.u32();
  uint32_t nwords = r.u32();
  rl.cache_bits.resize(nwords);
  for (uint32_t i = 0; i < nwords; ++i) rl.cache_bits[i] = r.u64();
  uint32_t nhits = r.u32();
  rl.cache_hits.resize(nhits);
  for (uint32_t i = 0; i < nhits; ++i) rl.cache_hits[i] = r.u32();
  uint32_t nreq = r.u32();
  rl.requests.resize(nreq);
  for (uint32_t i = 0; i < nreq; ++i) {
    rl.requests[i].rank = r.i32();
    rl.requests[i].cached = r.u8() != 0;
    rl.requests[i].cache_bit = r.u32();
    rl.requests[i].entry = ReadEntry(r);
  }
  return rl;
}

std::vector<uint8_t> SerializeResponseList(const ResponseList& rl) {
  Writer w;
  w.u32(kResponseMagic);
  w.u32(kWireVersion);
  w.i32(rl.join_last_rank);
  w.u8(rl.shutdown ? 1 : 0);
  w.u8(rl.cache_resync_needed ? 1 : 0);
  w.i64(rl.tuned_fusion_threshold);
  w.i32(rl.tuned_cycle_time_us);
  w.u32(static_cast<uint32_t>(rl.confirm_hashes.size()));
  for (uint64_t h : rl.confirm_hashes) w.u64(h);
  w.u32(static_cast<uint32_t>(rl.responses.size()));
  for (const Response& rs : rl.responses) {
    w.u8(static_cast<uint8_t>(rs.type));
    w.u8(static_cast<uint8_t>(rs.red_op));
    w.u8(static_cast<uint8_t>(rs.dtype));
    w.i32(rs.process_set_id);
    w.i32(rs.root_rank);
    w.i64(rs.total_bytes);
    w.str(rs.error);
    w.u32(static_cast<uint32_t>(rs.tensor_names.size()));
    for (const std::string& n : rs.tensor_names) w.str(n);
    for (const std::vector<int64_t>& shape : rs.tensor_shapes) {
      w.u8(static_cast<uint8_t>(shape.size()));
      for (int64_t d : shape) w.i64(d);
    }
  }
  return std::move(w.buf);
}

ResponseList ParseResponseList(const uint8_t* data, size_t len) {
  Reader r(data, len);
  if (r.u32() != kResponseMagic) throw std::runtime_error("bad response magic");
  if (r.u32() != kWireVersion) throw std::runtime_error("bad wire version");
  ResponseList rl;
  rl.join_last_rank = r.i32();
  rl.shutdown = r.u8() != 0;
  rl.cache_resync_needed = r.u8() != 0;
  rl.tuned_fusion_threshold = r.i64();
  rl.tuned_cycle_time_us = r.i32();
  uint32_t nconfirm = r.u32();
  rl.confirm_hashes.resize(nconfirm);
  for (uint32_t i = 0; i < nconfirm; ++i) rl.confirm_hashes[i] = r.u64();
  uint32_t n = r.u32();
  rl.responses.resize(n);
  for (uint32_t i = 0; i < n; ++i) {
    Response& rs = rl.responses[i];
    rs.type = static_cast<OpType>(r.u8());
    rs.red_op = static_cast<RedOp>(r.u8());
    rs.dtype = static_cast<DataType>(r.u8());
    rs.process_set_id = r.i32();
    rs.root_rank = r.i32();
    rs.total_bytes = r.i64();
    rs.error = r.str();
    uint32_t nt = r.u32();
    rs.tensor_names.resize(nt);
    for (uint32_t j = 0; j < nt; ++j) rs.tensor_names[j] = r.str();
    rs.tensor_shapes.resize(nt);
    for (uint32_t j = 0; j < nt; ++j) {
      uint8_t ndim = r.u8();
      rs.tensor_shapes[j].resize(ndim);
      for (uint8_t k = 0; k < ndim; ++k) rs.tensor_shapes[j][k] = r.i64();
    }
  }
  return rl;
}

}  // namespace hvt
