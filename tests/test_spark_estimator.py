"""Spark Estimator surface tests.

Mirrors the reference's integration pattern (SURVEY §4:
``test/integration/test_spark_torch.py`` / ``test_spark_keras.py`` on
local-mode Spark + temp Store): fit on synthetic data across 2 real
ranks via the local launcher, transform reproduces the trained model's
predictions, and the checkpoint lands in the Store.  Unit coverage for
Store/Params/data matches ``test_spark.py``'s store- and util-level
cases.
"""

import json
import os

import numpy as np
import pytest

from horovod_tpu.spark import LocalBackend, LocalStore
from horovod_tpu.spark.common import data as data_mod
from horovod_tpu.spark.common.params import EstimatorParams


def _regression_frame(n=256, d=4, seed=0):
    import pandas as pd

    rng = np.random.RandomState(seed)
    x = rng.rand(n, d).astype(np.float32)
    w = rng.randn(d, 1).astype(np.float32)
    y = x @ w + 0.01 * rng.randn(n, 1).astype(np.float32)
    return pd.DataFrame({"features": list(x), "label": list(y)}), x, y


def _classification_frame(n=256, d=4, k=3, seed=0):
    import pandas as pd

    rng = np.random.RandomState(seed)
    x = rng.rand(n, d).astype(np.float32)
    w = rng.randn(d, k).astype(np.float32)
    y = (x @ w).argmax(axis=1)
    return pd.DataFrame({"features": list(x), "label": y}), x, y


class TestStore:
    def test_layout_and_io(self, tmp_path):
        store = LocalStore(str(tmp_path / "store"))
        assert store.get_checkpoint_path("r1").endswith(
            os.path.join("runs", "r1", "checkpoints"))
        assert store.get_logs_path("r1").endswith(
            os.path.join("runs", "r1", "logs"))
        store.write_text("runs/r1/meta.json", json.dumps({"a": 1}))
        assert store.exists("runs/r1/meta.json")
        assert store.read_json("runs/r1/meta.json") == {"a": 1}
        assert store.get_checkpoints("r1") == []

    def test_create_factory_schemes(self, tmp_path):
        from horovod_tpu.spark import Store

        s = Store.create(f"file://{tmp_path}/s")
        assert isinstance(s, LocalStore.__mro__[1])  # FilesystemStore
        with pytest.raises(NotImplementedError, match="s3"):
            Store.create("s3://bucket/prefix")


class TestParams:
    def test_generated_accessors_roundtrip(self):
        p = EstimatorParams(epochs=5, feature_cols=["x"])
        assert p.getEpochs() == 5
        assert p.setBatchSize(64) is p  # chainable
        assert p.getBatchSize() == 64
        assert p.getFeatureCols() == ["x"]

    def test_unknown_param_rejected(self):
        with pytest.raises(ValueError, match="unknown param"):
            EstimatorParams(epohcs=5)


class TestDataMaterialization:
    def test_ragged_column_rejected(self):
        with pytest.raises(ValueError, match="ragged"):
            data_mod.to_columns(
                {"c": np.array([[1, 2], [1]], dtype=object)}, ["c"])

    def test_validation_fraction_split(self, tmp_path):
        df, x, y = _regression_frame()
        store = LocalStore(str(tmp_path))
        n_train, n_val = data_mod.materialize(
            df, store, ["features"], ["label"], validation=0.25, seed=3)
        assert n_train + n_val == len(df) and 0 < n_val < len(df)
        meta = store.read_json(store.get_data_metadata_path())
        assert meta["schema"]["features"]["shape"] == [4]

    def test_validation_indicator_column(self, tmp_path):
        import pandas as pd

        df, x, y = _regression_frame()
        df = df.assign(is_val=(np.arange(len(df)) % 4 == 0))
        store = LocalStore(str(tmp_path))
        n_train, n_val = data_mod.materialize(
            df, store, ["features"], ["label"], validation="is_val")
        assert n_val == len(df) // 4
        # the indicator column must not leak into the features
        shard = data_mod.load_shard(
            store.get_train_data_path(), data_mod.TRAIN_NPZ, 0, 1)
        assert set(shard) == {"features", "label"}

    def test_strided_shards_cover_all_rows(self, tmp_path):
        df, x, y = _regression_frame(n=101)
        store = LocalStore(str(tmp_path))
        data_mod.materialize(df, store, ["features"], ["label"])
        shards = [data_mod.load_shard(
            store.get_train_data_path(), data_mod.TRAIN_NPZ, r, 2)
            for r in range(2)]
        total = sum(len(s["label"]) for s in shards)
        assert total == 101
        merged = np.concatenate([s["features"] for s in shards])
        assert sorted(map(tuple, merged)) == sorted(map(tuple, x))


class TestTorchEstimator:
    def test_fit_transform_checkpoint_2proc(self, tmp_path):
        import torch
        import torch.nn as nn
        import torch.nn.functional as F

        from horovod_tpu.spark import TorchEstimator, TorchModel

        df, x, y = _regression_frame()
        model = nn.Sequential(nn.Linear(4, 1))
        est = TorchEstimator(
            model=model,
            optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
            loss=F.mse_loss,
            feature_cols=["features"], label_cols=["label"],
            batch_size=32, epochs=3, num_proc=2, verbose=0,
            validation=0.2, random_seed=7,
            store=LocalStore(str(tmp_path)))
        tm = est.fit(df)
        assert isinstance(tm, TorchModel)
        hist = tm.getHistory()
        # training must actually train, and validation must be tracked
        assert hist["loss"][-1] < hist["loss"][0]
        assert len(hist["val_loss"]) == 3
        # checkpoint landed in the Store
        ckpt = os.path.join(
            tm.getStore().get_checkpoint_path(tm.getRunId()),
            "checkpoint.pt")
        assert os.path.exists(ckpt)
        assert tm.getStore().get_checkpoints(tm.getRunId()) \
            == ["checkpoint.pt"]
        # transform reproduces the trained module's forward pass
        out = tm.transform(df)
        trained = tm.getModel()
        with torch.no_grad():
            direct = trained(torch.from_numpy(x)).numpy().reshape(-1)
        np.testing.assert_allclose(
            out["label__output"].to_numpy().astype(np.float32),
            direct, rtol=1e-5)
        # fitting must not mutate the user's original module in place
        #  (driver reloads into a deep copy)
        assert tm.getModel() is not model
        # dict-frame input round-trips too
        dict_out = tm.transform({"features": x, "label": y})
        np.testing.assert_allclose(
            np.asarray(dict_out["label__output"], dtype=np.float32),
            direct, rtol=1e-5)

    @pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
    def test_resume_from_checkpoint_2proc(self, tmp_path):
        """VERDICT r4 #8: refit with the same run_id and
        resume_from_checkpoint=True continues from the Store
        checkpoint — the second fit's first-epoch loss picks up near
        the first fit's last-epoch loss, not the fresh-weights loss."""
        import torch
        import torch.nn as nn
        import torch.nn.functional as F

        from horovod_tpu.spark import TorchEstimator

        df, _x, _y = _regression_frame()

        def make_est(resume):
            model = nn.Sequential(nn.Linear(4, 1))
            torch.manual_seed(3)
            for m in model:
                if hasattr(m, "reset_parameters"):
                    m.reset_parameters()
            return TorchEstimator(
                model=model,
                optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
                loss=F.mse_loss,
                feature_cols=["features"], label_cols=["label"],
                batch_size=32, epochs=2, num_proc=2, verbose=0,
                random_seed=7, run_id="resume_run",
                resume_from_checkpoint=resume,
                store=LocalStore(str(tmp_path)))

        first = make_est(resume=False).fit(df)
        h1 = first.getHistory()["loss"]
        assert h1[-1] < h1[0]
        # refit from the SAME fresh weights but resuming the run's
        # checkpoint: training continues from epoch 2's state
        second = make_est(resume=True).fit(df)
        h2 = second.getHistory()["loss"]
        # continues at (or below) roughly where the first fit ended —
        # far below the first fit's fresh-weights starting loss
        assert h2[0] < (h1[0] + h1[-1]) / 2
        assert h2[-1] <= h1[-1] * 1.5
        # a fresh fit WITHOUT resume restarts high (sanity check that
        # the assertion above is meaningful)
        fresh = make_est(resume=False).fit(df)
        assert fresh.getHistory()["loss"][0] > h2[0]

    @pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
    def test_sample_weight_col_2proc(self, tmp_path):
        """sample_weight_col (reference contract): the weight batch is
        the loss callable's third argument.  Half the rows carry a
        corrupted label with weight 0 — a weighted fit must learn the
        clean mapping anyway; an unweighted fit on the same frame must
        not (proves the weights actually flow)."""
        import pandas as pd
        import torch
        import torch.nn as nn

        from horovod_tpu.spark import TorchEstimator

        rng = np.random.RandomState(0)
        x = rng.rand(256, 4).astype(np.float32)
        w_true = rng.randn(4, 1).astype(np.float32)
        y = x @ w_true
        weight = np.ones(256, np.float32)
        weight[::2] = 0.0
        y_corrupt = y.copy()
        y_corrupt[::2] += 25.0  # zero-weighted rows are poisoned
        df = pd.DataFrame({"features": list(x),
                           "label": list(y_corrupt),
                           "w": weight})

        def weighted_mse(output, label, weight):
            return ((output - label) ** 2 * weight.unsqueeze(1)).sum() \
                / weight.sum().clamp(min=1.0)

        def make_est(loss, sw_col, run_id):
            model = nn.Sequential(nn.Linear(4, 1))
            torch.manual_seed(3)
            for m in model:
                if hasattr(m, "reset_parameters"):
                    m.reset_parameters()
            return TorchEstimator(
                model=model,
                optimizer=torch.optim.SGD(model.parameters(), lr=0.2),
                loss=loss, feature_cols=["features"],
                label_cols=["label"], sample_weight_col=sw_col,
                batch_size=32, epochs=4, num_proc=2, verbose=0,
                random_seed=7, run_id=run_id,
                store=LocalStore(str(tmp_path)))

        tm = make_est(weighted_mse, "w", "weighted").fit(df)
        pred = np.asarray(
            tm.transform({"features": x, "label": y})["label__output"],
            dtype=np.float32)
        clean_mse = float(((pred - y) ** 2).mean())
        assert clean_mse < 0.5  # learned the CLEAN mapping

        um = make_est(torch.nn.functional.mse_loss, None,
                      "unweighted").fit(df)
        upred = np.asarray(
            um.transform({"features": x, "label": y})["label__output"],
            dtype=np.float32)
        # pulled toward the +25 poisoned rows: far from clean labels
        assert float(((upred - y) ** 2).mean()) > clean_mse * 10

    @pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
    def test_loss_weights_and_gradient_compression_params(
            self, tmp_path):
        """Reference param spellings: loss_weights scales each
        output's loss (exactly 2x on the first step), and
        gradient_compression is accepted alongside compression."""
        import torch
        import torch.nn as nn
        import torch.nn.functional as F

        from horovod_tpu.spark import TorchEstimator

        df, _x, _y = _regression_frame()

        def make_est(run_id, **kw):
            model = nn.Sequential(nn.Linear(4, 1))
            torch.manual_seed(3)
            for m in model:
                if hasattr(m, "reset_parameters"):
                    m.reset_parameters()
            return TorchEstimator(
                model=model,
                optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
                loss=F.mse_loss, feature_cols=["features"],
                label_cols=["label"], batch_size=32, epochs=1,
                train_steps_per_epoch=1, num_proc=2, verbose=0,
                random_seed=7, run_id=run_id,
                store=LocalStore(str(tmp_path)), **kw)

        base = make_est("lw_base").fit(df).getHistory()["loss"][0]
        doubled = make_est(
            "lw_x2", loss_weights=[2.0]).fit(df).getHistory()["loss"][0]
        assert abs(doubled - 2.0 * base) < 1e-4 * max(abs(base), 1.0)

        # reference spelling of the compression knob
        est = make_est("gc_fp16", gradient_compression="fp16")
        assert est.getGradientCompression() == "fp16"
        h = est.fit(df).getHistory()["loss"]
        assert len(h) == 1

        # mismatched loss_weights length fails loudly
        with pytest.raises(Exception, match="loss_weights"):
            make_est("lw_bad", loss_weights=[1.0, 2.0]).fit(df)

    def test_sample_weight_col_driver_side_guards(self, tmp_path):
        import torch
        import torch.nn as nn

        from horovod_tpu.spark import TorchEstimator

        df, _x, _y = _regression_frame()
        df["w"] = 1.0
        model = nn.Sequential(nn.Linear(4, 1))

        def est(**kw):
            return TorchEstimator(
                model=model,
                optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
                feature_cols=["features"], label_cols=["label"],
                batch_size=32, epochs=1, num_proc=2,
                store=LocalStore(str(tmp_path)), **kw)

        # 2-arg module loss fails at fit() on the DRIVER, not with a
        # TypeError deep inside a worker rank
        with pytest.raises(ValueError, match="sample_weight"):
            est(loss=nn.MSELoss(), sample_weight_col="w").fit(df)
        # weights + transformation_fn would silently misalign rows
        with pytest.raises(ValueError, match="transformation_fn"):
            est(loss=lambda o, y, w: ((o - y) ** 2 * w).mean(),
                sample_weight_col="w",
                transformation_fn=lambda f, l: (f, l)).fit(df)

    def test_sample_weight_nonweight_third_arg_warns(self, tmp_path):
        """ADVICE r5: a REQUIRED third positional that doesn't look
        like a weight (focal's `gamma`) passes the arity gate but gets
        a warning naming the parameter — the weight batch is about to
        bind to a hyperparameter and train silently wrong."""
        import warnings

        import torch
        import torch.nn as nn

        from horovod_tpu.spark import TorchEstimator

        model = nn.Sequential(nn.Linear(4, 1))

        def est(loss):
            return TorchEstimator(
                model=model,
                optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
                feature_cols=["features"], label_cols=["label"],
                batch_size=32, epochs=1, num_proc=2,
                store=LocalStore(str(tmp_path)),
                loss=loss, sample_weight_col="w")

        def focal(output, target, gamma):
            return ((output - target) ** 2 * gamma).mean()

        with pytest.warns(UserWarning, match="'gamma'"):
            est(focal)._check_params()

        # a weight-named third arg stays silent
        def weighted(output, target, sample_weight):
            return ((output - target) ** 2 * sample_weight).mean()

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            est(weighted)._check_params()

    def test_sample_weight_strict_mode_errors(self, tmp_path,
                                              monkeypatch):
        """HVTPU_SPARK_STRICT upgrades the non-weight-third-arg warning
        to a hard error at fit() time, still naming the parameter —
        for pipelines that would rather fail than risk a silently
        misweighted model."""
        import torch
        import torch.nn as nn

        from horovod_tpu.spark import TorchEstimator

        model = nn.Sequential(nn.Linear(4, 1))

        def focal(output, target, gamma):
            return ((output - target) ** 2 * gamma).mean()

        est = TorchEstimator(
            model=model,
            optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
            feature_cols=["features"], label_cols=["label"],
            batch_size=32, epochs=1, num_proc=2,
            store=LocalStore(str(tmp_path)),
            loss=focal, sample_weight_col="w")

        monkeypatch.setenv("HVTPU_SPARK_STRICT", "1")
        with pytest.raises(ValueError, match="'gamma'"):
            est._check_params()
        # falsy spellings keep the warning behavior
        monkeypatch.setenv("HVTPU_SPARK_STRICT", "0")
        with pytest.warns(UserWarning, match="HVTPU_SPARK_STRICT"):
            est._check_params()

    def test_lightning_shim_raises_with_guidance(self):
        from horovod_tpu.spark.lightning import LightningEstimator

        with pytest.raises(ImportError, match="TorchEstimator"):
            LightningEstimator(model=object(), num_proc=2)

    def test_lightning_shim_upstream_name(self):
        # the reference exports the lightning estimator as
        # horovod.spark.lightning.TorchEstimator — same path here
        import horovod_tpu.spark.lightning as l

        assert l.TorchEstimator is l.LightningEstimator
        with pytest.raises(ImportError, match="migration.md"):
            l.TorchEstimator(model=object())

    def test_shard_smaller_than_batch_still_trains(self, tmp_path):
        """The tail batch must train (drop_last=False): 50 rows over 2
        ranks at batch_size=32 means every rank's shard (25 rows) is
        smaller than one batch."""
        import torch
        import torch.nn as nn
        import torch.nn.functional as F

        from horovod_tpu.spark import TorchEstimator

        df, x, y = _regression_frame(n=50)
        model = nn.Sequential(nn.Linear(4, 1))
        est = TorchEstimator(
            model=model,
            optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
            loss=F.mse_loss,
            feature_cols=["features"], label_cols=["label"],
            batch_size=32, epochs=4, num_proc=2, verbose=0,
            random_seed=7, store=LocalStore(str(tmp_path)))
        tm = est.fit(df)
        hist = tm.getHistory()
        assert hist["loss"][-1] < hist["loss"][0]
        assert all(v > 0 for v in hist["loss"])  # steps actually ran

    def test_compression_param_object_and_typo(self):
        """compression accepts the reference's object style and names a
        clear error for typos (shared resolve_compression)."""
        import horovod_tpu.torch as hvd_torch

        from horovod_tpu.spark.common.estimator import \
            resolve_compression

        assert resolve_compression(hvd_torch, None) \
            is hvd_torch.Compression.none
        assert resolve_compression(hvd_torch, "fp16") \
            is hvd_torch.Compression.fp16
        assert resolve_compression(
            hvd_torch, hvd_torch.Compression.fp16) \
            is hvd_torch.Compression.fp16
        with pytest.raises(ValueError, match="options"):
            resolve_compression(hvd_torch, "fp32")

    @pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
    def test_keras_uneven_shards_train_in_lockstep(self, tmp_path):
        """65 rows over 2 ranks: without the min-rows trim, rank 0
        runs one more gradient-allreduce batch than rank 1 and the
        epoch deadlocks."""
        import keras

        from horovod_tpu.spark import KerasEstimator

        df, x, y = _classification_frame(n=65)
        model = keras.Sequential([
            keras.layers.Input((4,)),
            keras.layers.Dense(3, activation="softmax"),
        ])
        est = KerasEstimator(
            model=model, optimizer=keras.optimizers.SGD(0.2),
            loss="sparse_categorical_crossentropy",
            feature_cols=["features"], label_cols=["label"],
            batch_size=32, epochs=2, num_proc=2, verbose=0,
            random_seed=7, store=LocalStore(str(tmp_path)))
        km = est.fit(df)
        assert len(km.getHistory()["loss"]) == 2

    def test_steps_cap_below_bps_raises(self, tmp_path):
        import torch
        import torch.nn as nn
        import torch.nn.functional as F

        from horovod_tpu.runner import RunError
        from horovod_tpu.spark import TorchEstimator

        df, x, y = _regression_frame(n=128)
        model = nn.Sequential(nn.Linear(4, 1))
        est = TorchEstimator(
            model=model,
            optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
            loss=F.mse_loss,
            feature_cols=["features"], label_cols=["label"],
            batch_size=16, epochs=1, num_proc=2, verbose=0,
            train_steps_per_epoch=1, backward_passes_per_step=2,
            store=LocalStore(str(tmp_path)))
        with pytest.raises(RunError, match="no optimizer step"):
            est.fit(df)

    def test_uneven_shards_bps_and_compression(self, tmp_path):
        """127 rows over 2 ranks (64/63-row shards would flip the
        per-rank batch count and deadlock without the rank-consistent
        step derivation) + backward_passes_per_step=2 local
        aggregation + fp16 wire compression, all through the estimator
        params (reference TorchEstimator knobs)."""
        import torch
        import torch.nn as nn
        import torch.nn.functional as F

        from horovod_tpu.spark import TorchEstimator

        df, x, y = _regression_frame(n=127)
        model = nn.Sequential(nn.Linear(4, 1))
        est = TorchEstimator(
            model=model,
            optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
            loss=F.mse_loss,
            feature_cols=["features"], label_cols=["label"],
            batch_size=16, epochs=4, num_proc=2, verbose=0,
            backward_passes_per_step=2, compression="fp16",
            random_seed=7, store=LocalStore(str(tmp_path)))
        tm = est.fit(df)
        hist = tm.getHistory()
        assert len(hist["loss"]) == 4
        assert hist["loss"][-1] < hist["loss"][0]

    def test_missing_params_raise(self, tmp_path):
        import torch.nn as nn

        from horovod_tpu.spark import TorchEstimator

        est = TorchEstimator(model=nn.Linear(2, 1),
                             feature_cols=["f"], label_cols=["l"],
                             store=LocalStore(str(tmp_path)))
        with pytest.raises(ValueError, match="optimizer"):
            est.fit({"f": np.zeros((4, 2)), "l": np.zeros(4)})


class TestKerasEstimator:
    @pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
    def test_fit_transform_checkpoint_2proc(self, tmp_path):
        import keras

        from horovod_tpu.spark import KerasEstimator, KerasModel

        df, x, y = _classification_frame()
        model = keras.Sequential([
            keras.layers.Input((4,)),
            keras.layers.Dense(8, activation="relu"),
            keras.layers.Dense(3, activation="softmax"),
        ])
        est = KerasEstimator(
            model=model, optimizer=keras.optimizers.SGD(0.2),
            loss="sparse_categorical_crossentropy",
            metrics=["accuracy"],
            feature_cols=["features"], label_cols=["label"],
            batch_size=32, epochs=3, num_proc=2, verbose=0,
            validation=0.2, random_seed=7,
            store=LocalStore(str(tmp_path)))
        km = est.fit(df)
        assert isinstance(km, KerasModel)
        hist = km.getHistory()
        assert hist["loss"][-1] < hist["loss"][0]
        assert set(hist) >= {"loss", "accuracy", "val_loss",
                             "val_accuracy"}
        ckpt_dir = km.getStore().get_checkpoint_path(km.getRunId())
        assert os.path.exists(os.path.join(ckpt_dir, "checkpoint.npz"))
        assert os.path.exists(os.path.join(ckpt_dir, "model.json"))
        out = km.transform(df)
        pred = np.stack(out["label__output"].to_numpy())
        assert pred.shape == (len(df), 3)
        # transform == the trained model's own predict
        direct = km.getModel().predict(x, verbose=0)
        np.testing.assert_allclose(pred, direct, rtol=1e-5)
        assert (pred.argmax(1) == y).mean() > 0.7


    @pytest.mark.slow  # tier-1 runtime diet: heaviest in the --durations audit; full matrix via -m slow
    def test_keras_resume_from_checkpoint_2proc(self, tmp_path):
        """Keras analog of the torch resume test: refit with the same
        run_id and resume_from_checkpoint=True loads the Store
        checkpoint over the shipped weights."""
        import keras

        from horovod_tpu.spark import KerasEstimator

        df, _x, _y = _classification_frame()

        def make_est(resume):
            keras.utils.set_random_seed(3)
            model = keras.Sequential([
                keras.layers.Input((4,)),
                keras.layers.Dense(8, activation="relu"),
                keras.layers.Dense(3, activation="softmax"),
            ])
            return KerasEstimator(
                model=model, optimizer=keras.optimizers.SGD(0.2),
                loss="sparse_categorical_crossentropy",
                feature_cols=["features"], label_cols=["label"],
                batch_size=32, epochs=2, num_proc=2, verbose=0,
                random_seed=7, run_id="keras_resume_run",
                resume_from_checkpoint=resume,
                store=LocalStore(str(tmp_path)))

        h1 = make_est(resume=False).fit(df).getHistory()["loss"]
        assert h1[-1] < h1[0]
        h2 = make_est(resume=True).fit(df).getHistory()["loss"]
        # resumes near the first fit's end, far below its start
        assert h2[0] < (h1[0] + h1[-1]) / 2
        # a fresh fit restarts high (the assertion above is meaningful)
        fresh = make_est(resume=False).fit(df).getHistory()["loss"]
        assert fresh[0] > h2[0]


class TestBackends:
    def test_local_backend_runs_across_ranks(self):
        backend = LocalBackend(num_proc=2)
        assert backend.num_processes() == 2

    def test_spark_backend_without_pyspark_defaults(self):
        from horovod_tpu.spark import SparkBackend

        b = SparkBackend()
        assert b.num_processes() == 2  # no pyspark here: default
