"""hvtpu.fleet: multi-job arbiter over one elastic pool.

Unit tier (tier-1, no real sleeps): the JobSpec validation matrix and
its ``hvtpufleet submit --spec`` exit-2 contract, the lifecycle state
machine, job-scoped KV prefixing, gang-scheduling edge cases (partial-
allocation refusal, never-fits fail-fast, victim tie-break
determinism, drain-grace expiry → charged restart), and every timer —
queue wait, autoscale debounce/cooldown, preemption grace — driven by
an injected fake clock through the ``core/clock`` seam.

Acceptance tier (slow, multiprocess): two real elastic jobs sharing a
localhost pool; a high-priority arrival preempts the low-priority job
through the graceful-drain channel (exit 79, ``--max-restarts 0``
proving zero budget strikes) and BOTH deliver every sample of every
epoch exactly once.
"""

import json
import os
import re
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from horovod_tpu.fleet import (DONE, DRAINING, FAILED, FleetArbiter,
                               FleetSpecError, Job, JobSpec, PENDING,
                               PlacementPolicy, QueueFullError,
                               RESIZING, RUNNING, Autoscaler,
                               SubmitJournal, TenantConfigError,
                               TorusGrid, prefixed_client)
from horovod_tpu.fleet.autoscale import FileSignal


# ---------------------------------------------------------------------------
# shared fakes
# ---------------------------------------------------------------------------


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def monotonic(self):
        return self.t

    def wall(self):
        return self.t

    def sleep(self, seconds):
        self.t += seconds

    def call_later(self, delay_s, fn):  # pragma: no cover
        raise AssertionError("no timers expected in these paths")


@pytest.fixture
def fake_clock():
    from horovod_tpu.core import clock as core_clock

    fc = _FakeClock(t=1000.0)
    core_clock.install(fc)
    try:
        yield fc
    finally:
        core_clock.install(None)


class _FakeDiscovery:
    """Mutable pool: duck-types HostDiscoveryScript."""

    def __init__(self, hosts=None):
        self.hosts = dict(hosts or {})

    def find_available_hosts_and_slots(self):
        return dict(self.hosts)


class _FakeRunner:
    """Handle-protocol fake: the test scripts the drain/relaunch
    transitions the real ElasticJobRunner derives from its driver."""

    def __init__(self, job):
        self.name = job.name
        self.charged_restarts = 0
        self.drains = 0
        self._phase = "pending"
        self._np = 0
        self._alloc = {}
        self._target = None
        self._exit = None
        self.shrink_requests = []
        self.escalations = 0
        self.stopped = False
        self.refuse_shrink = False
        self.started = False

    # -- handle protocol -------------------------------------------------
    def start(self, allocation):
        self.started = True
        self._alloc = dict(allocation)
        self._np = sum(allocation.values())
        self._phase = "running"

    def poll(self):
        return self._exit

    def phase(self):
        return self._phase

    def current_np(self):
        return self._np

    def target_np(self):
        return self._target

    def allocation(self):
        return dict(self._alloc)

    def request_shrink(self, new_np):
        if self.refuse_shrink or self._phase != "running":
            return False
        self.shrink_requests.append(new_np)
        self._target = new_np
        self._phase = "draining"
        return True

    def escalate(self):
        victims = self._np - (self._target or self._np)
        self.escalations += 1
        self.charged_restarts += 1  # bare SIGTERM = crash = charged
        self._apply_target()
        self._phase = "running"
        return victims

    def update_allocation(self, allocation):
        self._alloc = dict(allocation)
        self._np = sum(allocation.values())

    def stop(self):
        self.stopped = True

    # -- test scripting --------------------------------------------------
    def drain_lands(self):
        """Victims exited DRAIN_EXIT_CODE; the incarnation ended as a
        planned drain and the relaunch is in flight."""
        self.drains += 1
        self._apply_target()
        self._phase = "resizing"

    def relaunch(self):
        self._phase = "running"
        self._np = sum(self._alloc.values())

    def exit(self, code):
        self._exit = code
        self._alloc = {}

    def _apply_target(self):
        shed = self._np - self._target
        for h in sorted(self._alloc, reverse=True):
            if shed <= 0:
                break
            got = min(self._alloc[h], shed)
            self._alloc[h] -= got
            shed -= got
        self._alloc = {h: n for h, n in self._alloc.items() if n > 0}
        self._np = self._target
        self._target = None


def _spec(name, min_np=1, max_np=None, priority=0, **kw):
    return JobSpec(name, ["job-cmd"], min_np=min_np, max_np=max_np,
                   priority=priority, **kw)


@pytest.fixture
def arbiter(fake_clock):
    pool = _FakeDiscovery({"h1": 4, "h2": 4})
    events = []

    def event_fn(kind, **fields):
        events.append((kind.replace("fleet.", "", 1), fields))

    arb = FleetArbiter(pool, fleet_dir=None, tick_s=0.5,
                       drain_grace_s=30.0,
                       runner_factory=_FakeRunner, event_fn=event_fn,
                       register_debug=False)
    arb.pool = pool
    arb.events = events
    return arb


def _kinds(arb):
    return [k for k, _ in arb.events]


# ---------------------------------------------------------------------------
# JobSpec validation matrix (the submit exit-2 contract's engine)
# ---------------------------------------------------------------------------


_BAD_SPECS = [
    ("name", {"name": "bad name!"}),
    ("name", {"name": ""}),
    ("name", {"name": "-leading-dash"}),
    ("command", {"command": []}),
    ("command", {"command": "not-a-list"}),
    ("command", {"command": ["ok", ""]}),
    ("priority", {"priority": -1}),
    ("priority", {"priority": "hi"}),
    ("priority", {"priority": True}),
    ("min_np", {"min_np": 0}),
    ("min_np", {"min_np": 2.5}),
    ("max_np", {"min_np": 4, "max_np": 2}),
    ("max_np", {"max_np": 0}),
    ("env", {"env": {"A": 1}}),
    ("env", {"env": "PATH=x"}),
    ("max_restarts", {"max_restarts": -2}),
    ("restart_window", {"restart_window": -1}),
    ("drain_grace", {"drain_grace": 0.1}),
    ("autoscale.high", {"autoscale": {"low": 1}}),
    ("autoscale.low", {"autoscale": {"high": 1, "low": 2}}),
    ("autoscale.step", {"autoscale": {"high": 2, "low": 1, "step": 0}}),
    ("autoscale.debounce_s",
     {"autoscale": {"high": 2, "low": 1, "debounce_s": -1}}),
    ("autoscale.signal_file",
     {"autoscale": {"high": 2, "low": 1, "signal_file": ""}}),
    ("autoscale.bogus", {"autoscale": {"high": 2, "low": 1, "bogus": 1}}),
    ("frobnicate", {"frobnicate": 1}),
]


class TestJobSpecValidation:
    @pytest.mark.parametrize(
        "field,overlay", _BAD_SPECS,
        ids=[f"{f}-{i}" for i, (f, _) in enumerate(_BAD_SPECS)])
    def test_malformed_field_is_named(self, field, overlay):
        d = {"name": "ok", "command": ["run"]}
        d.update(overlay)
        with pytest.raises(FleetSpecError) as ei:
            JobSpec.from_dict(d)
        assert ei.value.field == field
        assert f"field '{field}'" in str(ei.value)

    @pytest.mark.parametrize("missing", ["name", "command"])
    def test_required_fields(self, missing):
        d = {"name": "ok", "command": ["run"]}
        del d[missing]
        with pytest.raises(FleetSpecError) as ei:
            JobSpec.from_dict(d)
        assert ei.value.field == missing

    def test_non_object_spec(self):
        with pytest.raises(FleetSpecError) as ei:
            JobSpec.from_dict([1, 2])
        assert ei.value.field == "spec"

    def test_load_invalid_json_and_missing_file(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text("{not json")
        with pytest.raises(FleetSpecError, match="invalid JSON") as ei:
            JobSpec.load(str(p))
        assert ei.value.field == "spec"
        with pytest.raises(FleetSpecError, match="unreadable"):
            JobSpec.load(str(tmp_path / "absent.json"))

    def test_round_trip(self):
        spec = JobSpec("train-a", ["python", "t.py"], priority=3,
                       min_np=2, max_np=8, env={"K": "v"},
                       max_restarts=2, restart_window=60.0,
                       drain_grace=5.0,
                       autoscale={"high": 10, "low": 2, "step": 2})
        again = JobSpec.from_dict(spec.to_dict())
        assert again.to_dict() == spec.to_dict()

    def test_effective_max(self):
        # max_np=None means "no cap": the pool bounds it
        assert _spec("a", min_np=2).effective_max(8) == 8
        assert _spec("a", min_np=2).effective_max() == 2
        assert _spec("a", min_np=2, max_np=16).effective_max(8) == 8
        assert _spec("a", min_np=2, max_np=4).effective_max(8) == 4


class TestLifecycle:
    def test_illegal_transition_raises(self, fake_clock):
        j = Job(_spec("a"), 1)
        with pytest.raises(RuntimeError, match="illegal transition"):
            j.to(RESIZING)
        j.to(RUNNING)
        j.to(DRAINING)
        j.to(RESIZING)
        j.to(RUNNING)
        j.to(DONE)
        assert j.terminal
        with pytest.raises(RuntimeError, match="illegal transition"):
            j.to(RUNNING)

    def test_queue_wait_stamped_once(self, fake_clock):
        j = Job(_spec("a"), 1)
        fake_clock.t += 12.5
        j.to(RUNNING)
        assert j.queue_wait_s == pytest.approx(12.5)


# ---------------------------------------------------------------------------
# job-scoped KV prefixing
# ---------------------------------------------------------------------------


class _StrKV:
    def __init__(self):
        self.d = {}

    def key_value_set(self, k, v):
        self.d[k] = v

    def blocking_key_value_get(self, k, timeout_ms):
        return self.d[k]

    def key_value_try_get(self, k):
        return self.d.get(k)

    def key_value_delete(self, k):
        self.d.pop(k, None)


class _DirKV(_StrKV):
    def key_value_dir_get(self, prefix):
        return sorted((k, v) for k, v in self.d.items()
                      if k.startswith(prefix))


class TestPrefixedClient:
    def test_jobs_are_namespaced_apart(self):
        inner = _DirKV()
        a = prefixed_client(inner, "job-a")
        b = prefixed_client(inner, "job-b")
        a.key_value_set("hvtdrain/0/notice/3", "x")
        assert "fleet/job-a/hvtdrain/0/notice/3" in inner.d
        assert b.key_value_try_get("hvtdrain/0/notice/3") is None
        assert a.key_value_try_get("hvtdrain/0/notice/3") == "x"

    def test_dir_get_reroots_results(self):
        inner = _DirKV()
        a = prefixed_client(inner, "job-a")
        a.key_value_set("hvtdrain/0/notice/1", "n1")
        a.key_value_set("hvtdrain/0/notice/2", "n2")
        prefixed_client(inner, "job-b").key_value_set(
            "hvtdrain/0/notice/9", "other")
        got = a.key_value_dir_get("hvtdrain/0/notice")
        assert got == [("hvtdrain/0/notice/1", "n1"),
                       ("hvtdrain/0/notice/2", "n2")]

    def test_capability_tiers_are_mirrored(self):
        assert not hasattr(prefixed_client(_StrKV(), "j"),
                           "key_value_dir_get")
        assert hasattr(prefixed_client(_DirKV(), "j"),
                       "key_value_dir_get")

    def test_delete_is_scoped(self):
        inner = _DirKV()
        a = prefixed_client(inner, "job-a")
        a.key_value_set("k", "v")
        a.key_value_delete("k")
        assert inner.d == {}


# ---------------------------------------------------------------------------
# gang scheduling + preemption edge cases (fake clock, fake runners)
# ---------------------------------------------------------------------------


class TestGangScheduling:
    def test_full_gang_or_nothing(self, arbiter):
        j1 = arbiter.submit(_spec("holder", min_np=5, max_np=5))
        j2 = arbiter.submit(_spec("waiter", min_np=5, max_np=5))
        arbiter.tick()
        assert j1.state == RUNNING and sum(j1.allocation.values()) == 5
        # 3 slots free < min_np=5: no partial allocation, no handle
        assert j2.state == PENDING
        assert j2.allocation == {} and j2.handle is None
        # the starvation is reported once, not every tick
        arbiter.tick()
        arbiter.tick()
        assert _kinds(arbiter).count("job_waiting") == 1

    def test_backfill_behind_starved_job(self, arbiter):
        arbiter.submit(_spec("holder", min_np=4, max_np=4))
        big = arbiter.submit(_spec("big", min_np=6, max_np=6))
        small = arbiter.submit(_spec("small", min_np=2, max_np=2))
        arbiter.tick()
        # big (4 free < 6) must not hold the pool idle: small backfills
        assert big.state == PENDING
        assert small.state == RUNNING

    def test_never_fits_fails_fast_with_diagnostic(self, arbiter):
        j = arbiter.submit(_spec("galaxy", min_np=100))
        arbiter.tick()
        assert j.state == FAILED
        assert "min_np=100" in j.reason
        assert "8 total slots" in j.reason
        assert "job_unschedulable_fatal" in _kinds(arbiter)

    def test_start_time_expansion_toward_max(self, arbiter):
        j = arbiter.submit(_spec("wide", min_np=2, max_np=6))
        arbiter.tick()
        assert sum(j.allocation.values()) == 6
        assert j.handle.started

    def test_no_expansion_while_another_waits(self, arbiter):
        wide = arbiter.submit(_spec("wide", min_np=2))  # uncapped
        arbiter.submit(_spec("waiter", min_np=20))  # > pool, pending...
        # ...but fail-fast kills it first; make it fit capacity
        arbiter.cancel("waiter")
        arbiter.submit(_spec("waiter2", min_np=7, max_np=7))
        arbiter.tick()
        # waiter2 keeps the pool contended: wide must stay at min_np
        assert sum(wide.allocation.values()) == 2

    def test_duplicate_live_name_rejected(self, arbiter):
        arbiter.submit(_spec("dup"))
        with pytest.raises(FleetSpecError) as ei:
            arbiter.submit(_spec("dup"))
        assert ei.value.field == "name"

    def test_resubmit_after_terminal_ok(self, arbiter):
        j = arbiter.submit(_spec("again", min_np=2, max_np=2))
        arbiter.tick()
        j.handle.exit(0)
        arbiter.tick()
        assert j.state == DONE
        j2 = arbiter.submit(_spec("again", min_np=2, max_np=2))
        arbiter.tick()
        assert j2.state == RUNNING

    def test_queue_wait_measured_on_injected_clock(self, arbiter,
                                                   fake_clock):
        arbiter.pool.hosts = {}
        j = arbiter.submit(_spec("late", min_np=4, max_np=4))
        arbiter.tick()
        assert j.state == PENDING
        fake_clock.t += 7.5
        arbiter.pool.hosts = {"h1": 4}
        arbiter.tick()
        assert j.state == RUNNING
        assert j.queue_wait_s == pytest.approx(7.5)
        start = [f for k, f in arbiter.events if k == "job_start"][0]
        assert start["queue_wait_s"] == pytest.approx(7.5)

    def test_cancel_pending_and_running(self, arbiter):
        p = arbiter.submit(_spec("p", min_np=20))
        assert arbiter.cancel("p") is True
        assert p.state == FAILED and p.reason == "cancelled"
        r = arbiter.submit(_spec("r", min_np=2, max_np=2))
        arbiter.tick()
        assert arbiter.cancel("r") is True
        assert r.handle.stopped
        r.handle.exit(1)
        arbiter.tick()
        assert r.state == FAILED and r.reason == "cancelled"
        assert arbiter.cancel("r") is False  # already terminal
        assert arbiter.cancel("ghost") is False

    def test_run_until_idle_on_fake_clock(self, fake_clock):
        class _InstantRunner(_FakeRunner):
            def poll(self):
                return 0 if self.started else None

        arb = FleetArbiter(_FakeDiscovery({"h1": 4}), fleet_dir=None,
                           tick_s=0.5, runner_factory=_InstantRunner,
                           register_debug=False)
        j = arb.submit(_spec("quick", min_np=2, max_np=2))
        t0 = fake_clock.t
        arb.run(until_idle=True)
        assert j.state == DONE
        # the loop slept on the SEAM (virtual time advanced, the test
        # thread never blocked on a real sleep)
        assert fake_clock.t > t0


class TestPreemption:
    def _running_pair(self, arbiter):
        """old holds 5 slots, young holds 3, pool (8) exhausted."""
        old = arbiter.submit(_spec("old-lo", min_np=2, max_np=5))
        young = arbiter.submit(_spec("young-lo", min_np=2, max_np=5))
        arbiter.tick()
        assert sum(old.allocation.values()) == 5
        assert sum(young.allocation.values()) == 3
        return old, young

    def test_youngest_victim_yields_first(self, arbiter):
        old, young = self._running_pair(arbiter)
        hi = arbiter.submit(_spec("hi", min_np=1, max_np=1, priority=5))
        arbiter.tick()
        # need 1: the YOUNGEST low-tier job sheds it — the older one
        # is untouched (tie-break: priority asc, submit_seq desc)
        assert young.state == DRAINING
        assert young.handle.shrink_requests == [2]
        assert old.state == RUNNING and old.handle.shrink_requests == []
        assert hi.state == PENDING  # gang waits for the drain

    def test_preempt_spreads_across_victims_toward_min(self, arbiter):
        old, young = self._running_pair(arbiter)
        arbiter.submit(_spec("hi", min_np=4, max_np=4, priority=5))
        arbiter.tick()
        # need 4 = young's 1 (floor min_np=2) + old's 3
        assert young.handle.shrink_requests == [2]
        assert old.handle.shrink_requests == [2]

    def test_never_below_min_reports_waiting(self, arbiter):
        old, young = self._running_pair(arbiter)
        hi = arbiter.submit(_spec("hi", min_np=5, max_np=5, priority=5))
        arbiter.tick()
        # reclaimable = 1+3 < 5: nobody is shrunk below min_np and the
        # arrival reports once
        assert old.state == RUNNING and young.state == RUNNING
        assert old.handle.shrink_requests == []
        assert hi.state == PENDING
        waits = [f for k, f in arbiter.events if k == "job_waiting"]
        assert len(waits) == 1 and waits[0]["missing"] == 1

    def test_drain_resize_lifecycle_and_latency(self, arbiter,
                                                fake_clock):
        old, young = self._running_pair(arbiter)
        hi = arbiter.submit(_spec("hi", min_np=4, max_np=4, priority=5))
        arbiter.tick()
        assert young.state == DRAINING and old.state == DRAINING
        fake_clock.t += 1.0
        young.handle.drain_lands()
        old.handle.drain_lands()
        arbiter.tick()
        assert young.state == RESIZING
        assert young.handle.drains == 1
        fake_clock.t += 0.5
        young.handle.relaunch()
        old.handle.relaunch()
        arbiter.tick()
        assert young.state == RUNNING and old.state == RUNNING
        assert young.preemptions == 1
        assert young.charged_restarts == 0  # planned: no strike
        assert old.charged_restarts == 0
        assert young.shrink_deadline is None  # fields cleared
        resized = [f for k, f in arbiter.events if k == "resized"]
        assert resized and resized[0]["resize_s"] == pytest.approx(1.5)
        # the freed gang admits the arrival
        assert hi.state == RUNNING
        assert sum(hi.allocation.values()) == 4

    def test_grace_expiry_escalates_and_charges(self, arbiter,
                                                fake_clock):
        _old, young = self._running_pair(arbiter)
        arbiter.submit(_spec("hi", min_np=1, max_np=1, priority=5))
        arbiter.tick()
        assert young.state == DRAINING
        # the victim ignores its notices; the grace window lapses
        fake_clock.t += 30.0
        arbiter.tick()
        assert young.handle.escalations == 1
        assert "drain_grace_expired" in _kinds(arbiter)
        arbiter.tick()
        # the SIGTERM relaunch is a CHARGED restart, by design
        assert young.charged_restarts == 1
        assert young.state == RUNNING
        # escalation fires exactly once per shrink
        fake_clock.t += 60.0
        arbiter.tick()
        assert young.handle.escalations == 1

    def test_shrink_retries_between_incarnations(self, arbiter):
        # one shrinkable victim: the holder is already at its min
        holder = arbiter.submit(_spec("holder", min_np=2, max_np=2))
        vict = arbiter.submit(_spec("vict", min_np=2, max_np=6))
        arbiter.tick()
        assert sum(vict.allocation.values()) == 6
        arbiter.submit(_spec("hi", min_np=4, max_np=4, priority=5))
        vict.handle.refuse_shrink = True
        arbiter.tick()
        assert vict.state == RUNNING  # between incarnations: deferred
        vict.handle.refuse_shrink = False
        arbiter.tick()
        assert vict.state == DRAINING
        assert vict.handle.shrink_requests == [2]
        assert holder.handle.shrink_requests == []

    def test_equal_priority_never_preempts(self, arbiter):
        old, young = self._running_pair(arbiter)
        peer = arbiter.submit(_spec("peer", min_np=3, max_np=3))
        arbiter.tick()
        arbiter.tick()
        assert peer.state == PENDING
        assert old.handle.shrink_requests == []
        assert young.handle.shrink_requests == []


# ---------------------------------------------------------------------------
# autoscaling: debounce / cooldown on the injected clock
# ---------------------------------------------------------------------------


class TestAutoscaler:
    def test_debounce_requires_sustained_signal(self):
        sig = {"v": 0.0}
        asc = Autoscaler(lambda: sig["v"], high=10, low=2, step=2,
                         debounce_s=5.0, cooldown_s=0.0)
        sig["v"] = 50.0
        assert asc.evaluate(now=100.0) is None  # first sighting arms
        assert asc.evaluate(now=103.0) is None  # < debounce_s
        sig["v"] = 5.0
        assert asc.evaluate(now=104.0) is None  # dip resets the timer
        sig["v"] = 50.0
        assert asc.evaluate(now=105.0) is None
        assert asc.evaluate(now=110.0) == ("grow", 2)

    def test_cooldown_blocks_thrash(self):
        asc = Autoscaler(lambda: 50.0, high=10, low=2, step=1,
                         debounce_s=0.0, cooldown_s=20.0)
        assert asc.evaluate(now=100.0) == ("grow", 1)
        assert asc.evaluate(now=105.0) is None
        assert asc.evaluate(now=119.9) is None
        assert asc.evaluate(now=121.0) == ("grow", 1)

    def test_shrink_on_low_watermark(self):
        sig = {"v": 1.0}
        asc = Autoscaler(lambda: sig["v"], high=10, low=2, step=3,
                         debounce_s=4.0, cooldown_s=0.0)
        assert asc.evaluate(now=10.0) is None
        assert asc.evaluate(now=14.0) == ("shrink", 3)

    def test_no_signal_resets_never_acts(self):
        seq = iter([50.0, None, 50.0, 50.0])
        asc = Autoscaler(lambda: next(seq), high=10, low=2,
                         debounce_s=3.0, cooldown_s=0.0)
        assert asc.evaluate(now=0.0) is None
        assert asc.evaluate(now=10.0) is None  # None resets the arm
        assert asc.evaluate(now=11.0) is None  # re-arms here
        assert asc.evaluate(now=14.0) == ("grow", 1)

    def test_inverted_watermarks_rejected(self):
        with pytest.raises(ValueError, match="inverted"):
            Autoscaler(lambda: None, high=1, low=2)

    def test_file_signal(self, tmp_path):
        p = tmp_path / "depth"
        sig = FileSignal(str(p))
        assert sig() is None  # absent file = no signal
        p.write_text("42.5\n")
        assert sig() == 42.5
        p.write_text("not a number")
        assert sig() is None

    def test_from_spec_env_fallback(self, tmp_path, monkeypatch):
        monkeypatch.delenv("HVTPU_FLEET_AUTOSCALE_SIGNAL_FILE",
                           raising=False)
        assert Autoscaler.from_spec({"high": 2, "low": 1}) is None
        monkeypatch.setenv("HVTPU_FLEET_AUTOSCALE_SIGNAL_FILE",
                           str(tmp_path / "s"))
        asc = Autoscaler.from_spec({"high": 2, "low": 1})
        assert isinstance(asc.signal_fn, FileSignal)

    def test_arbiter_grow_and_shrink(self, arbiter, fake_clock):
        j = arbiter.submit(_spec("serve", min_np=2, max_np=6))
        filler = arbiter.submit(_spec("filler", min_np=6, max_np=6))
        arbiter.tick()
        assert sum(j.allocation.values()) == 2  # filler contends
        sig = {"v": 5.0}
        arbiter.attach_autoscaler(
            "serve", Autoscaler(lambda: sig["v"], high=10, low=2,
                                step=2, debounce_s=1.0, cooldown_s=0.0))
        filler.handle.exit(0)
        arbiter.tick()
        assert filler.state == DONE
        # hot signal: grow by step after the debounce window
        sig["v"] = 40.0
        arbiter.tick()
        fake_clock.t += 1.0
        arbiter.tick()
        assert sum(j.allocation.values()) == 4
        assert j.handle.current_np() == 4
        # cold signal: shrink rides the SAME planned-drain channel
        sig["v"] = 1.0
        fake_clock.t += 5.0
        arbiter.tick()
        fake_clock.t += 1.0
        arbiter.tick()
        assert j.state == DRAINING
        assert j.handle.shrink_requests == [2]
        grow = [f for k, f in arbiter.events
                if k == "autoscale" and f["direction"] == "grow"]
        shrink = [f for k, f in arbiter.events
                  if k == "autoscale" and f["direction"] == "shrink"]
        assert len(grow) == 1 and len(shrink) == 1

    def test_grow_clamped_by_pool_and_max(self, arbiter, fake_clock):
        j = arbiter.submit(_spec("serve", min_np=2, max_np=3))
        arbiter.tick()
        assert sum(j.allocation.values()) == 3  # start-time expansion
        arbiter.attach_autoscaler(
            "serve", Autoscaler(lambda: 99.0, high=10, low=2, step=4,
                                debounce_s=0.0, cooldown_s=0.0))
        arbiter.tick()
        assert sum(j.allocation.values()) == 3  # max_np caps the grow


# ---------------------------------------------------------------------------
# spool protocol + CLI
# ---------------------------------------------------------------------------


def _write_spec(tmp_path, name="spooled", **overlay):
    d = {"name": name, "command": ["run"], "min_np": 2, "max_np": 2}
    d.update(overlay)
    p = tmp_path / f"{name}.json"
    p.write_text(json.dumps(d))
    return str(p)


@pytest.fixture
def fleet_dir(tmp_path):
    d = tmp_path / "fleet"
    (d / "submit").mkdir(parents=True)
    (d / "cancel").mkdir()
    return d


class TestSpoolProtocol:
    def _arbiter(self, fleet_dir):
        return FleetArbiter(_FakeDiscovery({"h1": 4}),
                            fleet_dir=str(fleet_dir), tick_s=0.5,
                            runner_factory=_FakeRunner,
                            register_debug=False)

    def test_spooled_spec_starts_and_state_published(self, fleet_dir,
                                                     fake_clock):
        arb = self._arbiter(fleet_dir)
        spec = _write_spec(fleet_dir / "submit")
        arb.tick()
        assert not os.path.exists(spec)  # consumed
        assert arb.jobs["spooled"].state == RUNNING
        state = json.loads((fleet_dir / "state.json").read_text())
        assert state["pool"]["slots_total"] == 4
        assert state["jobs"][0]["name"] == "spooled"
        assert state["jobs"][0]["state"] == "RUNNING"

    def test_malformed_spool_rejected_with_error_file(self, fleet_dir,
                                                      fake_clock):
        arb = self._arbiter(fleet_dir)
        _write_spec(fleet_dir / "submit", name="bad", min_np=0)
        arb.tick()
        assert "bad" not in arb.jobs
        err = (fleet_dir / "rejected" / "bad.json.error").read_text()
        assert "min_np" in err

    def test_cancel_marker(self, fleet_dir, fake_clock):
        arb = self._arbiter(fleet_dir)
        _write_spec(fleet_dir / "submit")
        arb.tick()
        (fleet_dir / "cancel" / "spooled").write_text("cancel\n")
        arb.tick()
        assert arb.jobs["spooled"].handle.stopped


class TestCrashRecovery:
    """``hvtpufleet serve`` restart: state.json + spool resume without
    double-launching anything (PR 15 satellite)."""

    def _arbiter(self, fleet_dir, events=None):
        def event_fn(kind, **fields):
            if events is not None:
                events.append((kind.replace("fleet.", "", 1), fields))

        return FleetArbiter(_FakeDiscovery({"h1": 4}),
                            fleet_dir=str(fleet_dir), tick_s=0.5,
                            runner_factory=_FakeRunner,
                            event_fn=event_fn, register_debug=False)

    def test_recover_resumes_running_job_once(self, fleet_dir,
                                              fake_clock):
        # incarnation 1: spool a job, tick it to RUNNING, then "crash"
        # (no close; the state.json published by the tick is all the
        # next incarnation gets)
        arb1 = self._arbiter(fleet_dir)
        _write_spec(fleet_dir / "submit")
        arb1.tick()
        assert arb1.jobs["spooled"].state == RUNNING
        arb1.jobs["spooled"].preemptions = 3
        arb1.jobs["spooled"].handle.charged_restarts = 1
        arb1.tick()  # publish the counters

        # the crash window: intake submitted the spec but died before
        # unlinking the spool file
        _write_spec(fleet_dir / "submit")

        events = []
        arb2 = self._arbiter(fleet_dir, events)
        assert arb2.recover() == 1
        job = arb2.jobs["spooled"]
        assert job.state == PENDING  # workers died with the arbiter
        assert job.preemptions == 3
        assert job.charged_restarts == 1
        assert ("recover", {"job": "spooled",
                            "prior_state": "RUNNING"}) in events

        arb2.tick()
        # the stale spool file is consumed as a duplicate, not
        # rejected, and the job gang-launches exactly once
        assert not os.path.exists(
            str(fleet_dir / "submit" / "spooled.json"))
        assert not os.path.exists(
            str(fleet_dir / "rejected" / "spooled.json.error"))
        assert "spool_duplicate" in [k for k, _ in events]
        assert "submit_rejected" not in [k for k, _ in events]
        assert arb2.jobs["spooled"].state == RUNNING
        assert arb2.jobs["spooled"].handle.started

    def test_recover_skips_terminal_jobs(self, fleet_dir, fake_clock):
        arb1 = self._arbiter(fleet_dir)
        _write_spec(fleet_dir / "submit", name="done-job")
        _write_spec(fleet_dir / "submit", name="live-job")
        arb1.tick()
        arb1.jobs["done-job"].handle.exit(0)
        arb1.tick()  # reaps done-job, publishes both rows
        assert arb1.jobs["done-job"].state == DONE

        arb2 = self._arbiter(fleet_dir)
        assert arb2.recover() == 1
        assert "done-job" not in arb2.jobs
        assert arb2.jobs["live-job"].state == PENDING

    def test_recover_without_state_json_is_a_noop(self, fleet_dir,
                                                  fake_clock):
        arb = self._arbiter(fleet_dir)
        assert arb.recover() == 0
        (fleet_dir / "state.json").write_text("{not json")
        assert arb.recover() == 0

    def test_recover_is_idempotent(self, fleet_dir, fake_clock):
        arb1 = self._arbiter(fleet_dir)
        _write_spec(fleet_dir / "submit")
        arb1.tick()

        arb2 = self._arbiter(fleet_dir)
        assert arb2.recover() == 1
        assert arb2.recover() == 0  # already live — nothing doubles
        assert len(arb2.jobs) == 1

    def test_changed_spool_spec_after_recovery_is_rejected(
            self, fleet_dir, fake_clock):
        # same name, *different* spec in the spool: that is a real
        # duplicate-name submit, not the crash window — reject it
        arb1 = self._arbiter(fleet_dir)
        _write_spec(fleet_dir / "submit")
        arb1.tick()
        _write_spec(fleet_dir / "submit", priority=7)

        events = []
        arb2 = self._arbiter(fleet_dir, events)
        arb2.recover()
        arb2.tick()
        err = (fleet_dir / "rejected" / "spooled.json.error").read_text()
        assert "already exists" in err
        assert arb2.jobs["spooled"].spec.priority == 0


class TestCLI:
    def _main(self, *argv):
        from tools.hvtpufleet.__main__ import main

        return main(list(argv))

    @pytest.mark.parametrize(
        "field,overlay", _BAD_SPECS,
        ids=[f"{f}-{i}" for i, (f, _) in enumerate(_BAD_SPECS)])
    def test_submit_malformed_exits_2_naming_field(
            self, tmp_path, capsys, field, overlay):
        spec = _write_spec(tmp_path, **overlay)
        rc = self._main("--fleet-dir", str(tmp_path), "submit",
                        "--spec", spec)
        assert rc == 2
        err = capsys.readouterr().err
        assert f"field '{field}'" in err
        # nothing reached the spool
        assert not os.path.exists(
            str(tmp_path / "submit" / "spooled.json"))

    def test_submit_invalid_json_exits_2(self, tmp_path, capsys):
        p = tmp_path / "broken.json"
        p.write_text("{oops")
        rc = self._main("--fleet-dir", str(tmp_path), "submit",
                        "--spec", str(p))
        assert rc == 2
        assert "invalid JSON" in capsys.readouterr().err

    def test_missing_fleet_dir_exits_2(self, tmp_path, capsys,
                                       monkeypatch):
        monkeypatch.delenv("HVTPU_FLEET_DIR", raising=False)
        spec = _write_spec(tmp_path)
        with pytest.raises(SystemExit) as ei:
            self._main("submit", "--spec", spec)
        assert ei.value.code == 2
        assert "HVTPU_FLEET_DIR" in capsys.readouterr().err

    def test_submit_appends_journal_record(self, tmp_path, fleet_dir,
                                           capsys):
        spec = _write_spec(tmp_path, name="good", priority=4)
        rc = self._main("--fleet-dir", str(fleet_dir), "submit",
                        "--spec", spec)
        assert rc == 0
        assert "submitted 'good'" in capsys.readouterr().out
        lines = (fleet_dir / "journal.jsonl").read_text().splitlines()
        rec = json.loads(lines[-1])
        assert rec["op"] == "submit" and rec["seq"] == 1
        assert rec["spec"]["priority"] == 4
        # nothing reaches the legacy spool dir any more
        assert os.listdir(fleet_dir / "submit") == []

    def test_submit_backpressured_when_queue_full(self, tmp_path,
                                                  fleet_dir, capsys,
                                                  monkeypatch):
        monkeypatch.setenv("HVTPU_FLEET_QUEUE_LIMIT", "2")
        for i in range(2):
            spec = _write_spec(tmp_path, name=f"j{i}")
            assert self._main("--fleet-dir", str(fleet_dir), "submit",
                              "--spec", spec) == 0
        spec = _write_spec(tmp_path, name="overflow")
        rc = self._main("--fleet-dir", str(fleet_dir), "submit",
                        "--spec", spec)
        assert rc == 75  # EX_TEMPFAIL: retry later
        err = capsys.readouterr().err
        assert "queue full" in err and "retry after" in err
        # the refused record never touched the journal
        lines = (fleet_dir / "journal.jsonl").read_text().splitlines()
        assert len(lines) == 2

    def test_list_without_server_exits_1(self, fleet_dir, capsys):
        rc = self._main("--fleet-dir", str(fleet_dir), "list")
        assert rc == 1
        assert "no state" in capsys.readouterr().err

    def test_list_renders_published_state(self, fleet_dir, fake_clock,
                                          capsys):
        arb = FleetArbiter(_FakeDiscovery({"h1": 4}),
                           fleet_dir=str(fleet_dir),
                           runner_factory=_FakeRunner,
                           register_debug=False)
        arb.submit(_spec("shown", min_np=2, max_np=2, priority=7))
        arb.tick()
        rc = self._main("--fleet-dir", str(fleet_dir), "list")
        out = capsys.readouterr().out
        assert rc == 0
        assert "4 slots" in out and "2 free" in out
        assert re.search(r"shown\s+RUNNING\s+7\s+2", out)
        rc = self._main("--fleet-dir", str(fleet_dir), "list", "--json")
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["jobs"][0][
            "name"] == "shown"

    def test_cancel_appends_journal_record(self, fleet_dir, capsys):
        rc = self._main("--fleet-dir", str(fleet_dir), "cancel", "byejob")
        assert rc == 0
        rec = json.loads(
            (fleet_dir / "journal.jsonl").read_text().splitlines()[-1])
        assert rec == {"op": "cancel", "name": "byejob", "seq": 1}


# ---------------------------------------------------------------------------
# acceptance: two real elastic jobs, one pool, preemption via drain
# ---------------------------------------------------------------------------

_DELIVER_RE = re.compile(
    r"DELIVER rank=(\d+) size=(\d+) gen=(\d+) epoch=(\d+) "
    r"idx=\[([0-9, ]*)\]")


def _job_env(deliver_log, epochs, samples, batch=4, sleep="0.25"):
    return {
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get(
            "PYTHONPATH", ""),
        "HVTPU_CPU_DEVICES": "1",
        "ELASTIC_EPOCHS": str(epochs),
        "DATA_SAMPLES": str(samples),
        "DATA_BATCH": str(batch),
        "EPOCH_SLEEP": sleep,
        "HVTPU_ELASTIC_DISCOVERY_INTERVAL": "0.2",
        "FLEET_DELIVER_LOG": deliver_log,
    }


def _assert_exactly_once(deliver_log, epochs, samples, label):
    text = open(deliver_log).read()
    per_epoch = {e: [] for e in range(epochs)}
    for m in _DELIVER_RE.finditer(text):
        idx = [int(v) for v in m.group(5).split(",") if v.strip()]
        per_epoch[int(m.group(4))].extend(idx)
    for e in range(epochs):
        got = sorted(per_epoch[e])
        assert got == list(range(samples)), (
            f"{label} epoch {e}: {len(got)} samples "
            f"({len(set(got))} unique) — exactly-once violated")


@pytest.mark.multiprocess
@pytest.mark.chaos
@pytest.mark.slow
def test_two_jobs_share_pool_preemption_drains_not_strikes(tmp_path):
    """ISSUE-14 chaos acceptance: a low-priority job holds the whole
    localhost pool; a high-priority arrival forces the arbiter to
    reclaim half of it THROUGH THE DRAIN CHANNEL.  The victims exit
    DRAIN_EXIT_CODE, the shrink costs no restart-budget strike (proven
    by ``max_restarts=0`` — any charged relaunch would FAIL the job),
    both jobs complete, and each delivers every sample of every epoch
    exactly once."""
    import time

    script = os.path.join(_REPO, "tests", "fleet_data_script.py")
    lo_log = str(tmp_path / "lo.deliver")
    hi_log = str(tmp_path / "hi.deliver")
    lo_epochs, lo_samples = 3, 32
    hi_epochs, hi_samples = 2, 16
    events = []
    arb = FleetArbiter(
        _FakeDiscovery({"localhost": 4}),
        fleet_dir=str(tmp_path / "fleet"),
        tick_s=0.3, drain_grace_s=60.0,
        event_fn=lambda kind, **f: events.append(
            (kind.replace("fleet.", "", 1), f)),
        register_debug=False)
    lo = arb.submit(JobSpec(
        "lo", [sys.executable, script], priority=0, min_np=2, max_np=4,
        max_restarts=0,
        env=_job_env(lo_log, lo_epochs, lo_samples)))
    deadline = time.time() + 300
    hi = None
    try:
        while time.time() < deadline:
            arb.tick()
            if hi is None and os.path.exists(lo_log) and os.path.getsize(
                    lo_log) > 0:
                # lo is mid-training at np=4: the arrival preempts it
                hi = arb.submit(JobSpec(
                    "hi", [sys.executable, script], priority=10,
                    min_np=2, max_np=2, max_restarts=0,
                    env=_job_env(hi_log, hi_epochs, hi_samples)))
            if arb.all_terminal():
                break
            time.sleep(0.3)
    finally:
        arb.close()
    assert hi is not None, "lo never delivered a batch"
    assert lo.state == DONE, (lo.state, lo.reason, events)
    assert hi.state == DONE, (hi.state, hi.reason, events)
    # the shrink went through the planned channel: a drain, no strike
    assert lo.preemptions == 1
    assert lo.handle.drains >= 1
    assert lo.charged_restarts == 0 and hi.charged_restarts == 0
    kinds = [k for k, _ in events]
    assert "preempt" in kinds and "resized" in kinds
    _assert_exactly_once(lo_log, lo_epochs, lo_samples, "lo")
    _assert_exactly_once(hi_log, hi_epochs, hi_samples, "hi")


# ---------------------------------------------------------------------------
# indexed intake: the journal protocol (PR 19 tentpole)
# ---------------------------------------------------------------------------


class TestIndexedIntake:
    def _arbiter(self, fleet_dir, events=None):
        def event_fn(kind, **fields):
            if events is not None:
                events.append((kind.replace("fleet.", "", 1), fields))

        return FleetArbiter(_FakeDiscovery({"h1": 4, "h2": 4}),
                            fleet_dir=str(fleet_dir), tick_s=0.5,
                            runner_factory=_FakeRunner,
                            event_fn=event_fn, register_debug=False)

    def test_intake_bounded_by_budget_per_tick(self, fleet_dir,
                                               fake_clock, monkeypatch):
        monkeypatch.setenv("HVTPU_FLEET_INTAKE_BUDGET", "3")
        arb = self._arbiter(fleet_dir)
        jr = SubmitJournal(str(fleet_dir))
        for i in range(8):
            jr.append_submit(
                _spec(f"j{i}", min_np=8, max_np=8).to_dict())
        arb.tick()
        assert len(arb.jobs) == 3  # one budget's worth, no more
        arb.tick()
        assert len(arb.jobs) == 6
        arb.tick()
        assert len(arb.jobs) == 8  # drained; cursor is caught up
        assert jr.depth() == 0

    def test_crash_between_apply_and_commit_is_exactly_once(
            self, fleet_dir, fake_clock):
        events = []
        arb1 = self._arbiter(fleet_dir, events)
        jr = SubmitJournal(str(fleet_dir))
        for i in range(3):
            jr.append_submit(_spec(f"j{i}").to_dict())
        arb1.tick()
        assert len(arb1.jobs) == 3
        # the crash window: state.json has the jobs, but the cursor
        # never committed — wipe it back to zero
        (fleet_dir / "journal.cursor").unlink()
        arb2 = self._arbiter(fleet_dir, events)
        assert arb2.recover() == 3
        arb2.tick()  # replays the whole journal against live jobs
        assert sorted(arb2.jobs) == ["j0", "j1", "j2"]
        kinds = [k for k, _ in events]
        assert kinds.count("journal_duplicate") == 3
        assert "submit_rejected" not in kinds  # never a dup-name error
        assert not (fleet_dir / "rejected").exists()

    def test_torn_tail_left_for_next_tick(self, fleet_dir, fake_clock):
        arb = self._arbiter(fleet_dir)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("whole").to_dict())
        torn = json.dumps({"op": "submit", "seq": 2,
                           "spec": _spec("torn").to_dict()})
        with open(fleet_dir / "journal.jsonl", "ab") as f:
            f.write(torn[:20].encode())  # no newline: mid-append crash
        arb.tick()
        assert sorted(arb.jobs) == ["whole"]
        # the writer completes the line: next tick picks it up
        with open(fleet_dir / "journal.jsonl", "ab") as f:
            f.write(torn[20:].encode() + b"\n")
        arb.tick()
        assert sorted(arb.jobs) == ["torn", "whole"]

    def test_corrupt_line_surfaced_not_fatal(self, fleet_dir,
                                             fake_clock):
        events = []
        arb = self._arbiter(fleet_dir, events)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("before").to_dict())
        with open(fleet_dir / "journal.jsonl", "ab") as f:
            f.write(b"{this is not json}\n")
        jr.append_submit(_spec("after").to_dict())
        arb.tick()
        assert sorted(arb.jobs) == ["after", "before"]
        kinds = [k for k, _ in events]
        assert "journal_corrupt" in kinds

    def test_backpressure_truthful_retry_after(self, fleet_dir,
                                               fake_clock, monkeypatch):
        monkeypatch.setenv("HVTPU_FLEET_QUEUE_LIMIT", "2")
        arb = self._arbiter(fleet_dir)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("a").to_dict())
        jr.append_submit(_spec("b").to_dict())
        with pytest.raises(QueueFullError) as ei:
            jr.append_submit(_spec("c").to_dict())
        assert ei.value.depth == 2 and ei.value.limit == 2
        assert ei.value.retry_after_s > 0
        assert "retry after" in str(ei.value)
        arb.tick()  # the arbiter drains the backlog...
        jr.append_submit(_spec("c").to_dict())  # ...and the retry lands
        arb.tick()
        assert "c" in arb.jobs

    def test_quiet_tick_reads_nothing(self, fleet_dir, fake_clock):
        arb = self._arbiter(fleet_dir)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("only").to_dict())
        arb.tick()
        cursor = json.loads((fleet_dir / "journal.cursor").read_text())
        batch = SubmitJournal(str(fleet_dir)).read_batch(256)
        assert batch == []  # O(new-entries): nothing new, nothing read
        assert cursor["seq"] == 1 and cursor["budget"] > 0

    def test_idle_tick_does_not_rewrite_cursor(self, fleet_dir,
                                               fake_clock):
        arb = self._arbiter(fleet_dir)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("only").to_dict())
        arb.tick()
        st = os.stat(fleet_dir / "journal.cursor")
        arb.tick()  # nothing new: no fsync'd rewrite of the cursor
        st2 = os.stat(fleet_dir / "journal.cursor")
        # atomic_write replaces via rename, so a rewrite would have
        # produced a fresh inode
        assert st2.st_ino == st.st_ino

    def test_cursor_commits_only_after_state_persists(
            self, fleet_dir, fake_clock, monkeypatch):
        # the CLI acked these submissions (exit 0): a crash after the
        # cursor commit but before state.json persists would lose them
        # forever, so the cursor must not advance until the admitted
        # jobs are durable
        arb1 = self._arbiter(fleet_dir)
        jr = SubmitJournal(str(fleet_dir))
        for i in range(3):
            jr.append_submit(_spec(f"j{i}").to_dict())

        def crash():
            raise RuntimeError("injected crash before state persist")

        monkeypatch.setattr(arb1, "_write_state_json", crash)
        with pytest.raises(RuntimeError):
            arb1.tick()
        # the batch was applied in memory but neither state.json nor
        # the cursor landed — a fresh incarnation must re-intake it
        cur = SubmitJournal(str(fleet_dir)).read_cursor()
        assert cur["seq"] == 0
        arb2 = self._arbiter(fleet_dir)
        assert arb2.recover() == 0  # nothing persisted, nothing lost
        arb2.tick()
        assert sorted(arb2.jobs) == ["j0", "j1", "j2"]

    def test_dead_writer_torn_tail_repaired_on_next_append(
            self, fleet_dir, fake_clock):
        events = []
        arb = self._arbiter(fleet_dir, events)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("first").to_dict())
        with open(fleet_dir / "journal.jsonl", "ab") as f:
            # a writer crashed mid-append and is never coming back
            f.write(b'{"op": "submit", "seq": 2, "spec"')
        # the next writer must terminate the dead fragment before
        # appending, or its (acked!) record merges into the torn line
        # and both are dropped as one corrupt record
        jr.append_submit(_spec("second").to_dict())
        arb.tick()
        assert sorted(arb.jobs) == ["first", "second"]
        kinds = [k for k, _ in events]
        assert "journal_corrupt" in kinds  # the fragment, surfaced

    def test_tail_seq_survives_oversized_record(self, fleet_dir,
                                                fake_clock):
        jr = SubmitJournal(str(fleet_dir))
        big = _spec("big", env={"BLOB": "x" * 200_000}).to_dict()
        assert jr.append_submit(big) == 1  # one line > the 64KB window
        # seq numbering must continue, not restart at 1 (duplicate
        # seqs would break depth() and cursor-based dedup)
        assert jr.append_submit(_spec("next").to_dict()) == 2
        assert jr.depth() == 2


# ---------------------------------------------------------------------------
# cancel race: spooled-but-not-intaken jobs (PR 19 satellite)
# ---------------------------------------------------------------------------


class TestCancelRace:
    def _arbiter(self, fleet_dir, events):
        def event_fn(kind, **fields):
            events.append((kind.replace("fleet.", "", 1), fields))

        return FleetArbiter(_FakeDiscovery({"h1": 4}),
                            fleet_dir=str(fleet_dir), tick_s=0.5,
                            runner_factory=_FakeRunner,
                            event_fn=event_fn, register_debug=False)

    def test_journal_cancel_lands_before_schedule(self, fleet_dir,
                                                  fake_clock):
        # submit + cancel both in the backlog when the arbiter wakes:
        # flock ordering guarantees cancel-after-submit, so the job
        # must die in the SAME tick, never reaching the scheduler
        events = []
        arb = self._arbiter(fleet_dir, events)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("doomed").to_dict())
        jr.append_cancel("doomed")
        arb.tick()
        j = arb.jobs["doomed"]
        assert j.state == FAILED and j.reason == "cancelled"
        assert j.handle is None  # never launched
        kinds = [k for k, _ in events]
        assert "job_start" not in kinds

    def test_journal_cancel_tombstones_legacy_spool(self, fleet_dir,
                                                    fake_clock):
        # the PR 14 race: `hvtpufleet cancel` for a job whose spec
        # still sits in the legacy spool dir.  The cancel record must
        # tombstone the file so the job NEVER surfaces as PENDING.
        events = []
        arb = self._arbiter(fleet_dir, events)
        _write_spec(fleet_dir / "submit", name="spooled")
        SubmitJournal(str(fleet_dir)).append_cancel("spooled")
        arb.tick()
        assert "spooled" not in arb.jobs
        assert not os.path.exists(fleet_dir / "submit" / "spooled.json")
        kinds = [k for k, _ in events]
        assert "cancel_spooled" in kinds and "job_start" not in kinds

    def test_legacy_marker_tombstones_same_tick_spool(self, fleet_dir,
                                                      fake_clock):
        events = []
        arb = self._arbiter(fleet_dir, events)
        _write_spec(fleet_dir / "submit", name="racer")
        (fleet_dir / "cancel" / "racer").write_text("cancel\n")
        arb.tick()
        # markers are processed FIRST: the same-tick spool file is
        # consumed by the tombstone, not started
        assert "racer" not in arb.jobs
        assert "cancel_spooled" in [k for k, _ in events]

    def test_cancel_for_unknown_job_reports_once(self, fleet_dir,
                                                 fake_clock):
        events = []
        arb = self._arbiter(fleet_dir, events)
        SubmitJournal(str(fleet_dir)).append_cancel("ghost")
        arb.tick()
        assert [k for k, _ in events].count("cancel_unknown") == 1


# ---------------------------------------------------------------------------
# admission control: tenant quota edge matrix (PR 19 satellite)
# ---------------------------------------------------------------------------


class TestAdmissionQuotas:
    def _arbiter(self, fleet_dir, events, hosts=None):
        def event_fn(kind, **fields):
            events.append((kind.replace("fleet.", "", 1), fields))

        return FleetArbiter(_FakeDiscovery(hosts or {"h1": 4, "h2": 4}),
                            fleet_dir=str(fleet_dir), tick_s=0.5,
                            runner_factory=_FakeRunner,
                            event_fn=event_fn, register_debug=False)

    def _tenants(self, fleet_dir, table, mtime=None):
        p = fleet_dir / "tenants.json"
        p.write_text(json.dumps(table))
        if mtime is not None:
            os.utime(p, (mtime, mtime))

    def test_queued_quota_rejects_naming_tenant_and_limit(
            self, fleet_dir, fake_clock):
        events = []
        self._tenants(fleet_dir, {"acme": {"max_queued": 1}}, mtime=1)
        arb = self._arbiter(fleet_dir, events, hosts={"h1": 1})
        jr = SubmitJournal(str(fleet_dir))
        for i in range(3):
            jr.append_submit(
                _spec(f"q{i}", min_np=1, tenant="acme").to_dict())
        arb.tick()
        # during one intake batch the first is queued, the rest are
        # refused with the tenant and the limit named
        rejects = [f for k, f in events if k == "submit_rejected"]
        assert len(rejects) == 2
        for f in rejects:
            assert "tenant 'acme'" in f["error"]
            assert "max_queued=1" in f["error"]
        errs = sorted(os.listdir(fleet_dir / "rejected"))
        assert errs == ["journal-2.error", "journal-3.error"]

    def test_max_ranks_quota_defers_start_without_blocking_pool(
            self, fleet_dir, fake_clock):
        events = []
        self._tenants(fleet_dir, {"acme": {"max_ranks": 4}}, mtime=1)
        arb = self._arbiter(fleet_dir, events)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("a", min_np=4, tenant="acme").to_dict())
        jr.append_submit(_spec("b", min_np=2, tenant="acme").to_dict())
        jr.append_submit(_spec("c", min_np=2, tenant="other").to_dict())
        arb.tick()
        # quota exactly met: a (4 ranks) starts; b parks on policy; the
        # OTHER tenant's job backfills freely past the parked one
        assert arb.jobs["a"].state == RUNNING
        assert arb.jobs["b"].state == PENDING
        assert arb.jobs["c"].state == RUNNING
        waits = [f for k, f in events if k == "quota_wait"]
        assert len(waits) == 1 and waits[0]["job"] == "b"
        assert "max_ranks=4" in waits[0]["detail"]

    def test_quota_shrink_below_usage_never_kills_running(
            self, fleet_dir, fake_clock):
        events = []
        self._tenants(fleet_dir, {"acme": {"max_ranks": 8}}, mtime=1)
        arb = self._arbiter(fleet_dir, events)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("big", min_np=6, tenant="acme").to_dict())
        arb.tick()
        assert arb.jobs["big"].state == RUNNING
        # the operator tightens the quota BELOW current usage: running
        # jobs are never revoked, but new starts defer
        self._tenants(fleet_dir, {"acme": {"max_ranks": 2}}, mtime=2)
        jr.append_submit(_spec("next", min_np=1, tenant="acme").to_dict())
        arb.tick()
        assert arb.jobs["big"].state == RUNNING  # untouched
        assert arb.jobs["next"].state == PENDING
        assert "tenants_reload" in [k for k, _ in events]
        # the quota frees up when big exits
        arb.jobs["big"].handle.exit(0)
        arb.tick()
        assert arb.jobs["next"].state == RUNNING

    def test_malformed_tenants_json_keeps_previous_table(
            self, fleet_dir, fake_clock):
        events = []
        self._tenants(fleet_dir, {"acme": {"max_queued": 0}}, mtime=1)
        arb = self._arbiter(fleet_dir, events)
        jr = SubmitJournal(str(fleet_dir))
        jr.append_submit(_spec("x", tenant="acme").to_dict())
        arb.tick()  # max_queued=0: refused outright
        assert "x" not in arb.jobs
        # a broken rewrite must NOT drop the quota table
        (fleet_dir / "tenants.json").write_text("{broken")
        os.utime(fleet_dir / "tenants.json", (3, 3))
        jr.append_submit(_spec("y", tenant="acme").to_dict())
        arb.tick()
        assert "y" not in arb.jobs  # previous table still enforced
        bad = [f for k, f in events if k == "tenants_rejected"]
        assert bad and "previous table kept" in bad[0]["error"]

    def test_tenant_field_errors_are_named(self):
        from horovod_tpu.fleet import AdmissionController

        ac = AdmissionController(None)
        with pytest.raises(TenantConfigError) as ei:
            ac.load_dict({"acme": {"weight": -1}})
        assert "tenant 'acme'" in str(ei.value)
        assert "weight" in str(ei.value)
        with pytest.raises(TenantConfigError, match="unknown field"):
            ac.load_dict({"acme": {"max_rank": 4}})
        with pytest.raises(TenantConfigError, match="must be an object"):
            ac.load_dict({"acme": "not-an-object"})

    def test_spec_tenant_validation(self):
        with pytest.raises(FleetSpecError, match="tenant"):
            JobSpec("j", ["cmd"], tenant="bad tenant!")
        assert JobSpec("j", ["cmd"]).tenant_key == "default"
        assert JobSpec("j", ["cmd"], tenant="acme").tenant_key == "acme"
        d = JobSpec("j", ["cmd"], tenant="acme").to_dict()
        assert d["tenant"] == "acme"
        assert "tenant" not in JobSpec("j", ["cmd"]).to_dict()


# ---------------------------------------------------------------------------
# starvation guard: aging on the fake clock (PR 19 satellite)
# ---------------------------------------------------------------------------


class TestStarvationGuard:
    def test_aged_job_boosts_over_every_tier(self, arbiter, fake_clock,
                                             monkeypatch):
        monkeypatch.setenv("HVTPU_FLEET_STARVATION_SECONDS", "100")
        hog = arbiter.submit(_spec("hog", min_np=2, max_np=8,
                                   priority=9))
        arbiter.tick()
        assert sum(hog.allocation.values()) == 8  # pool exhausted
        lo = arbiter.submit(_spec("lo", min_np=4, priority=0))
        arbiter.tick()
        # below the threshold: a lower tier never preempts upward
        assert hog.state == RUNNING and lo.state == PENDING
        assert hog.handle.shrink_requests == []
        fake_clock.t += 101.0
        arbiter.tick()
        # aged: boosted over the higher tier, the hog drains toward min
        aged = [f for k, f in arbiter.events if k == "job_aged"]
        assert len(aged) == 1 and aged[0]["job"] == "lo"
        assert aged[0]["waited_s"] >= 100.0
        assert hog.state == DRAINING
        assert hog.handle.shrink_requests == [4]
        pre = [f for k, f in arbiter.events if k == "preempt"]
        assert pre and pre[0]["reason"] == "preempted for lo"
        # the drain lands; the aged job's wait stays bounded
        hog.handle.drain_lands()
        hog.handle.relaunch()
        arbiter.tick()
        assert lo.state == RUNNING
        assert lo.queue_wait_s <= 101.0 + 1.0

    def test_aging_disabled_at_zero(self, arbiter, fake_clock,
                                    monkeypatch):
        monkeypatch.setenv("HVTPU_FLEET_STARVATION_SECONDS", "0")
        hog = arbiter.submit(_spec("hog", min_np=2, max_np=8,
                                   priority=9))
        arbiter.tick()
        lo = arbiter.submit(_spec("lo", min_np=4, priority=0))
        fake_clock.t += 100000.0
        arbiter.tick()
        assert lo.state == PENDING and hog.state == RUNNING
        assert "job_aged" not in [k for k, _ in arbiter.events]

    def test_aged_event_fires_once(self, arbiter, fake_clock,
                                   monkeypatch):
        monkeypatch.setenv("HVTPU_FLEET_STARVATION_SECONDS", "50")
        arbiter.submit(_spec("hog", min_np=2, max_np=8, priority=9))
        arbiter.tick()
        arbiter.submit(_spec("lo", min_np=8, priority=0))
        fake_clock.t += 51.0
        arbiter.tick()
        arbiter.tick()
        arbiter.tick()
        aged = [k for k, _ in arbiter.events if k == "job_aged"]
        assert len(aged) == 1


# ---------------------------------------------------------------------------
# topology-aware placement (PR 19 tentpole)
# ---------------------------------------------------------------------------


class TestPlacement:
    def test_torus_grid_shape_and_distance(self):
        g = TorusGrid(["h0", "h1", "h2", "h3", "h4", "h5"])
        # 6 hosts fold onto a 2x3 (rows x cols) torus
        assert g.cols == 3 and g.rows == 2
        assert g.distance("h0", "h1") == 1
        assert g.distance("h0", "h2") == 1  # row wrap: 2 -> 0
        assert g.distance("h0", "h3") == 1  # column neighbour
        assert set(g.neighbors("h0")) <= {"h1", "h2", "h3"}

    def test_partial_grid_neighbors_agree_with_distance(self):
        # non-square pools leave the last torus row partial; wraps
        # must fold within the valid extent instead of being dropped,
        # so connectivity (fragmentation's view) and proximity (the
        # carver's view) describe the same geometry
        for n in (3, 5, 7, 10, 13, 32):
            g = TorusGrid([f"h{i:02d}" for i in range(n)])
            for h in g.names:
                nbs = g.neighbors(h)
                assert h not in nbs and len(nbs) == len(set(nbs))
                if n > 1:
                    assert nbs, f"n={n}: {h} isolated"
                for nb in nbs:
                    assert g.distance(h, nb) == 1, (n, h, nb)
            # the fully-free pool is one connected region — hosts in
            # the partial row included
            p = PlacementPolicy()
            free = {h: 1 for h in g.names}
            assert p.fragmentation(free, g.names) == 0.0, n

    def test_best_fit_picks_tightest_single_host(self):
        p = PlacementPolicy()
        pool = {"h1": 8, "h2": 8, "h3": 8}
        free = {"h1": 4, "h2": 8, "h3": 3}
        alloc = p.carve(free, 3, pool)
        # h3 (3 free) is the TIGHTEST host that still fits the gang:
        # taking it whole leaves the big holes intact
        assert alloc == {"h3": 3}
        assert free == {"h1": 4, "h2": 8, "h3": 0}  # mutated in place

    def test_multi_host_gang_stays_contiguous(self):
        p = PlacementPolicy()
        hosts = [f"h{i:02d}" for i in range(16)]  # a 4x4 torus
        pool = {h: 8 for h in hosts}
        free = {h: 8 for h in hosts}
        alloc = p.carve(free, 24, pool)  # 3 hosts' worth
        g = p.grid_for(pool)
        names = sorted(alloc)
        # every member is within torus distance 1 of the anchor set
        assert len(names) == 3
        assert max(g.distance(names[0], h) for h in names) <= 2

    def test_expansion_carves_near_existing_allocation(self):
        p = PlacementPolicy()
        hosts = [f"h{i:02d}" for i in range(9)]  # 3x3
        pool = {h: 8 for h in hosts}
        free = {h: 8 for h in hosts}
        free.pop("h04")
        grown = p.carve(free, 8, pool, near={"h04": 8})
        (picked,) = grown
        g = p.grid_for(pool)
        assert g.distance("h04", picked) == 1  # a torus neighbour

    def test_fragmentation_metric(self):
        p = PlacementPolicy()
        hosts = [f"h{i:02d}" for i in range(4)]  # 2x2
        pool = {h: 4 for h in hosts}
        # fully free: one component, zero fragmentation
        assert p.fragmentation({h: 4 for h in hosts}, pool) == 0.0
        # no free at all: defined as zero, not a division crash
        assert p.fragmentation({}, pool) == 0.0
        # on a 2x2 torus every pair is adjacent, so split the free
        # space across a larger ring to isolate components
        hosts6 = [f"g{i}" for i in range(6)]
        pool6 = {h: 4 for h in hosts6}
        g6 = p.grid_for(pool6)
        a = hosts6[0]
        far = max(hosts6, key=lambda h: g6.distance(a, h))
        frag = p.fragmentation({a: 4, far: 4}, pool6)
        assert 0.0 < frag <= 0.5  # two equal islands -> half stranded

    def test_fragmentation_gauge_published_by_tick(self, arbiter):
        from horovod_tpu.obs import metrics as obs_metrics

        arbiter.submit(_spec("j", min_np=3))
        arbiter.tick()
        g = obs_metrics.gauge("hvtpu_fleet_fragmentation")
        assert 0.0 <= g.value() <= 1.0
