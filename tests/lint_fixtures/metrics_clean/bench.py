"""bench fixture (clean): the metric contract the bench guard enforces."""

REQUIRED_METRIC_KEYS = [
    "hvtpu_fixture_steps_total",
]
