"""Build glue: compile the native control-plane core at install time.

Parity role: the reference's ``setup.py`` + ``CMakeLists.txt`` compile
``horovod/common`` into the wheel (SURVEY.md §2.3).  Here the native
core is a plain C-ABI shared library (no Python includes — it is
loaded via ctypes by ``horovod_tpu/native/core.py``), so the standard
``build_ext`` machinery is overridden to emit
``horovod_tpu/native/libhvt_core.so`` instead of a Python extension
module.

The build is OPTIONAL: on a box without a C++ toolchain the wheel
ships without the library and the runtime falls back to the Python
twin (``native/fallback.py``), exactly like a source checkout where
the lazy ``make`` in ``native/core.py`` fails.
"""

import os

from setuptools import Extension, setup
from setuptools.command.build_ext import build_ext

_SRC = [
    "horovod_tpu/native/src/message.cc",
    "horovod_tpu/native/src/controller.cc",
    "horovod_tpu/native/src/thread_pool.cc",
    "horovod_tpu/native/src/timeline.cc",
    "horovod_tpu/native/src/gaussian_process.cc",
    "horovod_tpu/native/src/c_api.cc",
]
_CXXFLAGS = ["-O2", "-std=c++17", "-fPIC", "-Wall", "-Wextra",
             "-pthread"]


class SharedLib(Extension):
    """A plain shared library (ctypes-loaded), not a Python module."""


class BuildNativeCore(build_ext):
    def get_ext_filename(self, fullname):
        if fullname.endswith("libhvt_core"):
            # fixed name, no python-version/ABI suffix: core.py loads
            # exactly "libhvt_core.so" next to itself
            parts = fullname.split(".")[:-1] + ["libhvt_core.so"]
            return os.path.join(*parts)
        return super().get_ext_filename(fullname)

    def build_extension(self, ext):
        if not isinstance(ext, SharedLib):
            return super().build_extension(ext)
        try:
            objects = self.compiler.compile(
                ext.sources,
                output_dir=self.build_temp,
                extra_postargs=_CXXFLAGS,
            )
            out = self.get_ext_fullpath(ext.name)
            os.makedirs(os.path.dirname(out), exist_ok=True)
            # distutils links with the C driver; name libstdc++
            # explicitly or the .so carries unresolved C++ runtime
            # symbols and fails at dlopen
            self.compiler.link_shared_object(
                objects, out, libraries=["stdc++"],
                extra_postargs=["-pthread"],
            )
        except Exception as e:  # noqa: BLE001 — optional native build
            self.warn(
                f"native core build failed ({e}); the wheel will use "
                "the pure-Python control-plane twin "
                "(horovod_tpu.native.fallback)"
            )


setup(
    ext_modules=[
        SharedLib("horovod_tpu.native.libhvt_core", sources=_SRC),
    ],
    cmdclass={"build_ext": BuildNativeCore},
)
