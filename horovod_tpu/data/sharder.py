"""Deterministic sample-space sharding with resize re-sharding.

The unit of truth is a **seeded per-epoch permutation** over the global
sample indices plus one **global cursor** — the number of samples the
whole world has consumed this epoch.  Both are pure functions of
committed state (``seed``, ``epoch``, ``cursor``), never of rank or
world size, which is what makes elastic resizes lossless:

* Every rank computes the same ``permutation(seed, epoch)``.
* At each step the world consumes one contiguous window
  ``perm[cursor : cursor + min(size * batch_size, n - cursor)]`` and
  splits it contiguously across ranks (``np.array_split`` semantics:
  piece sizes differ by at most one, possibly empty on a short tail).
* After a generation change the *unconsumed remainder* ``perm[cursor:]``
  is simply re-split across the NEW world — no sample in the remainder
  is repeated or dropped, because the cursor (restored from the elastic
  commit) marks exactly what was already delivered.

Because the step count per epoch — ``ceil((n - cursor0) /
(size * batch_size))`` — is itself a function of shared state, every
rank agrees on the epoch-end boundary without communication; the
loader's allreduce-min length agreement (docs/data.md) only exists to
catch *sources* that disagree about ``n`` across hosts.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def epoch_permutation(num_samples: int, seed: int, epoch: int,
                      shuffle: bool = True) -> np.ndarray:
    """The global sample order for one epoch: a permutation of
    ``arange(num_samples)`` drawn from a ``(seed, epoch)``-keyed RNG —
    identical on every rank and across elastic incarnations, different
    per epoch.  ``shuffle=False`` returns the identity order."""
    if num_samples < 0:
        raise ValueError(f"num_samples must be >= 0, got {num_samples}")
    if not shuffle:
        return np.arange(num_samples, dtype=np.int64)
    rng = np.random.Generator(
        np.random.PCG64(np.random.SeedSequence([int(seed), int(epoch)])))
    return rng.permutation(num_samples).astype(np.int64)


def world_batch(size: int, batch_size: int) -> int:
    """Samples the whole world consumes per step."""
    if size < 1 or batch_size < 1:
        raise ValueError(
            f"size and batch_size must be >= 1, got {size}, {batch_size}")
    return size * batch_size


def steps_remaining(num_samples: int, cursor: int, size: int,
                    batch_size: int) -> int:
    """Steps left in the epoch from ``cursor`` — the same number on
    every rank (it depends only on shared state), so no rank can run
    past its peers into a deadlocked collective."""
    left = max(num_samples - cursor, 0)
    wb = world_batch(size, batch_size)
    return -(-left // wb)  # ceil


def step_window(num_samples: int, cursor: int, size: int,
                batch_size: int) -> int:
    """How many samples the world consumes at THIS step (the full
    ``size * batch_size`` mid-epoch, the ragged remainder on the final
    step)."""
    return min(world_batch(size, batch_size), max(num_samples - cursor, 0))


def shard_window(perm: np.ndarray, cursor: int, rank: int, size: int,
                 batch_size: int) -> Tuple[np.ndarray, int]:
    """(this rank's sample indices for the step, the new global cursor).

    The step window is split contiguously: rank ``r`` takes the ``r``-th
    piece of ``np.array_split(window, size)``.  On a full window every
    piece is exactly ``batch_size``; on the epoch's ragged tail pieces
    differ by at most one sample and trailing ranks may get an empty
    batch (route those through ``hvt.join()`` if the training loop runs
    a collective per batch — see docs/data.md).
    """
    if not 0 <= rank < size:
        raise ValueError(f"rank {rank} out of range for size {size}")
    n = len(perm)
    take = step_window(n, cursor, size, batch_size)
    window = perm[cursor:cursor + take]
    piece = np.array_split(window, size)[rank]
    return piece.astype(np.int64), cursor + take


class Sharder:
    """Per-epoch permutation cache over the pure functions above."""

    def __init__(self, num_samples: int, batch_size: int, seed: int = 0,
                 shuffle: bool = True):
        self.num_samples = int(num_samples)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self._cached_epoch: int = -1
        self._cached_perm: np.ndarray = np.empty(0, dtype=np.int64)

    def permutation(self, epoch: int) -> np.ndarray:
        if epoch != self._cached_epoch:
            self._cached_perm = epoch_permutation(
                self.num_samples, self.seed, epoch, self.shuffle)
            self._cached_epoch = epoch
        return self._cached_perm

    def steps_remaining(self, cursor: int, size: int) -> int:
        return steps_remaining(self.num_samples, cursor, size,
                               self.batch_size)

    def next_indices(self, epoch: int, cursor: int, rank: int,
                     size: int) -> Tuple[np.ndarray, int]:
        """This rank's indices for the step starting at ``cursor``,
        plus the post-step global cursor."""
        return shard_window(self.permutation(epoch), cursor, rank, size,
                            self.batch_size)
