"""Host discovery for elastic training.

Parity surface: ``horovod/runner/elastic/discovery.py``
(``HostDiscoveryScript``, ``HostManager``) — a user-provided executable
prints the currently-available ``host:slots`` lines; the driver polls it
on an interval and reacts to diffs, maintaining a blacklist of hosts
that failed.

Departure from upstream: the reference blacklist is PERMANENT (a host
that strikes out never runs again, even after a reboot fixes it).
Here blacklisting is a **cooldown** with exponential re-admission —
strike ``k`` sidelines a host for ``base * 2**(k-1)`` seconds (capped),
after which it is probed again; a successful incarnation decays its
strike count.  A flaky-but-recovering host rejoins the world instead of
shrinking it forever, while a persistently bad host backs off toward
the cap and contributes almost no churn.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
from typing import Dict, List, Optional

from ..core import clock
from ..runner import hosts as hosts_mod

logger = logging.getLogger("horovod_tpu")


class HostDiscoveryScript:
    """Runs the user's discovery script and parses its output (parity:
    HostDiscoveryScript.find_available_hosts_and_slots)."""

    def __init__(self, script: str, timeout: float = 30.0):
        self.script = script
        self.timeout = timeout

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.run(
            self.script, shell=True, capture_output=True, text=True,
            timeout=self.timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"host discovery script failed ({out.returncode}): "
                f"{out.stderr.strip()[:500]}"
            )
        slots: Dict[str, int] = {}
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            hs = hosts_mod.parse_host_spec(line)
            for h in hs:
                slots[h.hostname] = slots.get(h.hostname, 0) + h.slots
        return slots


class _BlacklistEntry:
    __slots__ = ("strikes", "until")

    def __init__(self):
        self.strikes = 0
        self.until = 0.0


class HostManager:
    """Tracks current hosts, computes diffs, maintains the cooldown
    blacklist (parity: HostManager + the blacklist in
    horovod/runner/elastic/registration.py, with re-admission added —
    see the module docstring)."""

    def __init__(self, discovery: HostDiscoveryScript,
                 cooldown_base_s: Optional[float] = None,
                 cooldown_max_s: Optional[float] = None):
        self._discovery = discovery
        self.current: Dict[str, int] = {}
        self.last_found: Dict[str, int] = {}
        self._blacklist: Dict[str, _BlacklistEntry] = {}
        self.cooldown_base_s = (
            float(os.environ.get("HVTPU_BLACKLIST_COOLDOWN_SECONDS",
                                 "300"))
            if cooldown_base_s is None else cooldown_base_s)
        self.cooldown_max_s = (
            float(os.environ.get("HVTPU_BLACKLIST_COOLDOWN_MAX_SECONDS",
                                 "3600"))
            if cooldown_max_s is None else cooldown_max_s)

    # -- blacklist ------------------------------------------------------
    def blacklist_host(self, hostname: str,
                       now: Optional[float] = None) -> float:
        """Record a strike: sideline ``hostname`` for ``base *
        2**(strikes-1)`` seconds (capped) before it is probed again.
        Returns the cooldown applied."""
        now = clock.monotonic() if now is None else now
        entry = self._blacklist.setdefault(hostname, _BlacklistEntry())
        entry.strikes += 1
        cooldown = min(
            self.cooldown_max_s,
            self.cooldown_base_s * (2.0 ** (entry.strikes - 1)))
        entry.until = now + cooldown
        return cooldown

    def record_success(self, hostname: str) -> None:
        """Decay one strike after an incarnation where this host's
        workers all exited cleanly (done or reset-requested); at zero
        strikes the entry is forgotten entirely."""
        entry = self._blacklist.get(hostname)
        if entry is None:
            return
        entry.strikes -= 1
        if entry.strikes <= 0:
            del self._blacklist[hostname]

    def blacklisted_now(self, now: Optional[float] = None) -> List[str]:
        """Hosts currently inside a cooldown window."""
        now = clock.monotonic() if now is None else now
        return sorted(h for h, e in self._blacklist.items()
                      if e.until > now)

    def strikes(self, hostname: str) -> int:
        entry = self._blacklist.get(hostname)
        return entry.strikes if entry is not None else 0

    def next_readmission_s(self, now: Optional[float] = None
                           ) -> Optional[float]:
        """Seconds until the soonest cooldown expires, or None when no
        host is currently sidelined."""
        now = clock.monotonic() if now is None else now
        pending = [e.until - now for e in self._blacklist.values()
                   if e.until > now]
        return min(pending) if pending else None

    # -- blacklist-hint persistence ------------------------------------
    # The blacklist lives in driver memory; a driver restart (or a
    # coordinator-loss relaunch that rebuilds the driver's world view)
    # would otherwise forget which hosts were striking out and happily
    # re-elect a bad host as coordinator.  Hints persist strikes plus
    # REMAINING cooldown (``until`` is monotonic-clock relative, so the
    # absolute deadline cannot cross processes) to the elastic state
    # dir and merge conservatively on load (max of strikes/cooldowns).

    def save_hints(self, path: str,
                   now: Optional[float] = None) -> None:
        """Atomically persist the blacklist as restart-survivable
        hints; best-effort (a hint write failure must not fail the
        incarnation bookkeeping that triggered it)."""
        now = clock.monotonic() if now is None else now
        doc = {h: {"strikes": e.strikes,
                   "cooldown_remaining_s": max(0.0, e.until - now)}
               for h, e in sorted(self._blacklist.items())}
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            logger.warning("could not persist blacklist hints to %s",
                           path, exc_info=True)

    def load_hints(self, path: str,
                   now: Optional[float] = None) -> int:
        """Merge persisted hints into the live blacklist (strikes and
        remaining cooldowns take the max of disk vs memory).  Returns
        the number of hosts hinted; missing/corrupt files are zero."""
        now = clock.monotonic() if now is None else now
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return 0
        loaded = 0
        for hostname, hint in doc.items():
            try:
                strikes = int(hint["strikes"])
                remaining = float(hint.get("cooldown_remaining_s", 0.0))
            except (TypeError, KeyError, ValueError):
                continue
            entry = self._blacklist.setdefault(hostname,
                                               _BlacklistEntry())
            entry.strikes = max(entry.strikes, strikes)
            entry.until = max(entry.until, now + max(0.0, remaining))
            loaded += 1
        return loaded

    # -- discovery ------------------------------------------------------
    def refresh(self, now: Optional[float] = None) -> bool:
        """Poll discovery; returns True if the effective host set
        changed (additions, removals, or a cooldown expiring/engaging,
        after blacklist filtering)."""
        found = self._discovery.find_available_hosts_and_slots()
        self.last_found = dict(found)
        cooling = set(self.blacklisted_now(now))
        effective = {
            h: s for h, s in found.items() if h not in cooling
        }
        changed = effective != self.current
        self.current = effective
        return changed

    def exhausted(self, min_np: int,
                  now: Optional[float] = None) -> bool:
        """True when the last discovery succeeded yet EVERY discovered
        host is inside a cooldown window.  Unlike the old permanent
        blacklist this is no longer hopeless — the driver consults
        ``next_readmission_s`` to decide whether waiting out the
        soonest cooldown fits its deadline."""
        del min_np  # reserved for smarter policies
        if not self.last_found:
            return False
        cooling = set(self.blacklisted_now(now))
        return all(h in cooling for h in self.last_found)

    def available_slots(self) -> int:
        return sum(self.current.values())

    def host_spec(self) -> str:
        return ",".join(
            f"{h}:{s}" for h, s in sorted(self.current.items())
        )
