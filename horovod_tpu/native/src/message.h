// Wire format for controller coordination messages.
//
// Parity: horovod/common/message.cc + horovod/common/wire/message.fbs
// (Request / RequestList / Response / ResponseList, FlatBuffers).  We
// use a hand-rolled little-endian length-prefixed format instead of
// FlatBuffers: the blobs ride the JAX coordination-service KV store
// (which replaces MPI_Gatherv/MPI_Bcast of the reference controller),
// so all we need is compact, versioned, deterministic bytes.
#pragma once

#include <cstring>
#include <stdexcept>

#include "common.h"

namespace hvt {

constexpr uint32_t kRequestMagic = 0x52545648;   // "HVTR"
constexpr uint32_t kResponseMagic = 0x50545648;  // "HVTP"
// v2: ResponseList carries coordinator-tuned (fusion threshold, cycle
// time) so every rank applies identical autotuned parameters.
// v3: RequestList grows the steady-state `cache_bits` frame (bypass
// cycles send a per-rank cache-bit vector instead of serialized
// requests) + bypass/resync flags; ResponseList carries
// `cache_resync_needed` to force full-request cycles on divergence.
// v5 (v4 was an ABI-only bump): RequestList carries the atomic
// burst-unit delimiter (burst_id/burst_len right after the flags byte)
// and a `predicted` confirmation flag (bit 4); ResponseList carries
// `confirm_hashes` (FNV-1a 64 of each suppressed fully-predicted
// component's would-be response bytes).
constexpr uint32_t kWireVersion = 5;

// A request as sent rank -> coordinator. Parity: message.h Request.
struct Request {
  int32_t rank = 0;
  Entry entry;          // metadata of the op this rank declares ready
  bool cached = false;  // true: only cache_bit below is meaningful
  uint32_t cache_bit = 0;
};

// A rank's per-cycle message. Parity: RequestList (with its `shutdown`
// flag; we add `joined` like EnqueueJoin's special request).
struct RequestList {
  int32_t rank = 0;
  std::vector<Request> requests;
  std::vector<uint32_t> cache_hits;  // bit ids of cached pending requests
  bool joined = false;
  bool shutdown = false;
  // Steady-state bypass cycle: `requests` is empty and the drained ops
  // travel as set bits in `cache_bits` (u64 words, little-endian bit
  // order within a word).
  bool cache_bypass = false;
  // Periodic full resync: requests carry FULL entries so the
  // coordinator's message table / stall inspector re-anchor on truth.
  bool cache_resync = false;
  // Post-hoc confirmation of a locally predicted schedule: the rank
  // already executed PredictResponses(cache_bits) and only expects a
  // confirm hash back, not a ResponseList.
  bool predicted = false;
  // Atomic burst unit: this drain's first burst_len requests (or, on a
  // bypass blob, its first burst_len cache bits in ascending order)
  // form one indivisible unit — released and fused together, never
  // across the boundary.  0 = no unit (empty drains, membership
  // frames, resync re-announcements).
  uint32_t burst_id = 0;
  uint32_t burst_len = 0;
  std::vector<uint64_t> cache_bits;
};

// Confirm-hash function for suppressed predicted components.  Must
// match wire.py's fnv1a64 byte-for-byte.
uint64_t Fnv1a64(const uint8_t* data, size_t n);

// Pack ascending bit ids into a u64-word bitvector / back.  The byte
// layout (and therefore the bit order produced by UnpackBits) must
// match wire.py's bits_to_words/words_to_bits exactly.
std::vector<uint64_t> PackBits(const std::vector<uint32_t>& bits);
std::vector<uint32_t> UnpackBits(const std::vector<uint64_t>& words);

// Coordinator decision for one fused batch. Parity: message.h Response:
// one Response may carry many tensor names that execute as a single
// fused collective.
struct Response {
  OpType type = OpType::kAllreduce;
  RedOp red_op = RedOp::kSum;
  DataType dtype = DataType::kFloat32;
  int32_t process_set_id = 0;
  int32_t root_rank = -1;
  std::vector<std::string> tensor_names;
  // Per-tensor shapes, parallel to tensor_names.  The reference carries
  // shapes only in Requests; we echo them in Responses so every rank can
  // rebuild the full cache entry from the response blob alone — that is
  // what keeps ResponseCache bit ids identical across ranks even for
  // process-set-restricted ops.
  std::vector<std::vector<int64_t>> tensor_shapes;
  int64_t total_bytes = 0;
  std::string error;  // non-empty => error response (parity: Response::ERROR)
};

// Parity: ResponseList + `shutdown` flag.
struct ResponseList {
  std::vector<Response> responses;
  int32_t join_last_rank = -1;  // >=0 once every rank joined
  bool shutdown = false;
  // Coordinator could not expand a bypass cache bit: every rank must
  // send a full-resync request blob next cycle (re-announcing its
  // in-flight ops) so the message table heals.
  bool cache_resync_needed = false;
  // coordinator-tuned parameters (-1 = unset)
  int64_t tuned_fusion_threshold = -1;
  int32_t tuned_cycle_time_us = -1;
  // One FNV-1a 64 hash per suppressed fully-predicted burst component
  // (in release order): every announcing rank predicted the identical
  // schedule, so the coordinator emits the hash of the would-be
  // response bytes instead of the responses themselves.
  std::vector<uint64_t> confirm_hashes;
};

// ---------------------------------------------------------------------------
// byte writer/reader
// ---------------------------------------------------------------------------

class Writer {
 public:
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void u32(uint32_t v) { raw(&v, 4); }
  void i32(int32_t v) { raw(&v, 4); }
  void i64(int64_t v) { raw(&v, 8); }
  void u64(uint64_t v) { raw(&v, 8); }
  void f64(double v) { raw(&v, 8); }
  void str(const std::string& s) {
    u32(static_cast<uint32_t>(s.size()));
    raw(s.data(), s.size());
  }
  void raw(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), b, b + n);
  }
};

class Reader {
 public:
  Reader(const uint8_t* p, size_t n) : p_(p), end_(p + n) {}
  uint8_t u8() { return *take(1); }
  uint32_t u32() { uint32_t v; memcpy(&v, take(4), 4); return v; }
  int32_t i32() { int32_t v; memcpy(&v, take(4), 4); return v; }
  int64_t i64() { int64_t v; memcpy(&v, take(8), 8); return v; }
  uint64_t u64() { uint64_t v; memcpy(&v, take(8), 8); return v; }
  double f64() { double v; memcpy(&v, take(8), 8); return v; }
  std::string str() {
    uint32_t n = u32();
    const uint8_t* p = take(n);
    return std::string(reinterpret_cast<const char*>(p), n);
  }

 private:
  const uint8_t* take(size_t n) {
    if (p_ + n > end_) throw std::runtime_error("hvt wire: short read");
    const uint8_t* r = p_;
    p_ += n;
    return r;
  }
  const uint8_t* p_;
  const uint8_t* end_;
};

std::vector<uint8_t> SerializeRequestList(const RequestList& rl);
RequestList ParseRequestList(const uint8_t* data, size_t len);
std::vector<uint8_t> SerializeResponseList(const ResponseList& rl);
ResponseList ParseResponseList(const uint8_t* data, size_t len);

}  // namespace hvt
