"""Elastic data-parallel training example.

The horovod_tpu analog of the reference's elastic examples
(examples/elastic/pytorch/pytorch_mnist_elastic.py shape): state
commits every epoch survive worker loss and world resizes.

Input rides :class:`hvt.data.ElasticDataLoader`: the loader's
``(epoch, cursor, seed)`` state is registered with the elastic state,
so a resize re-splits only the UNCONSUMED remainder of the epoch across
the new world (the old naive ``rank/size`` slicing restarted the epoch
and re-visited samples) and a graceful preemption resumes mid-epoch
from the drain-committed cursor.  Every full step hands each rank
exactly ``batch_size`` samples regardless of world size, so compiled
shapes survive resizes too (only an epoch's ragged tail batch varies).

Run:
  hvtpurun --host-discovery-script ./discover.sh --min-np 2 \
      --cpu-devices 1 python examples/elastic_train.py
where discover.sh prints e.g. "localhost:4".
"""

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvt
import horovod_tpu.elastic as elastic
from horovod_tpu.data import ArraySource, ElasticDataLoader


def main():
    hvt.init()
    rng = np.random.RandomState(0)
    x = rng.rand(512, 32).astype(np.float32)
    w_true = rng.randn(32, 1).astype(np.float32)
    y = (x @ w_true).astype(np.float32)

    loader = ElasticDataLoader(
        ArraySource({"x": x, "y": y}), batch_size=64, seed=1234)

    params = {"w": jnp.zeros((32, 1))}
    state = elastic.JaxState(params=params, data=loader.state)

    @jax.jit
    def grad_fn(p, bx, by):
        def loss(p):
            return jnp.mean((bx @ p["w"] - by) ** 2)

        return jax.value_and_grad(loss)(p)

    @elastic.run
    def train(state):
        while loader.state.epoch < 8:
            loss = None
            # resumes at the committed mid-epoch cursor after a resize
            for batch in loader:
                loss, grads = grad_fn(state.params, batch["x"],
                                      batch["y"])
                grads = {
                    k: hvt.allreduce(g, op=hvt.Average)
                    for k, g in grads.items()
                }
                state.params = jax.tree.map(
                    lambda p, g: p - 0.05 * g, state.params, grads
                )
            state.commit()
            if hvt.rank() == 0:
                print(
                    f"epoch {loader.state.epoch}: "
                    f"loss={float(loss):.5f} "
                    f"(world size {hvt.size()})",
                    flush=True,
                )
        loader.close()
        if hvt.rank() == 0:
            print("elastic training complete", flush=True)

    train(state)


if __name__ == "__main__":
    main()
