"""rank-divergence pass: collectives under rank-dependent control flow.

Every rank must issue the same collectives in the same order, or the
job deadlocks (or — since PR 4 — dies with HvtpuMismatchError at
runtime).  This pass is the static counterpart: it flags calls whose
name matches a known collective when they are lexically nested under
an `if` / `while` / ternary whose test depends on the caller's rank.

Rank-dependence is detected on the test expression:

  * a call to rank()/local_rank()/cross_rank()/node_rank()/
    process_index()/rank_in_process_set()
  * an attribute read ending in .rank / .local_rank / .cross_rank
    (e.g. ``state.rank == 0``)
  * a local name assigned from either of the above earlier in the
    same scope (dataflow-lite taint, single forward pass)

Intentional root-rank-only patterns (checkpoint save on rank 0 that
still issues a barrier, etc.) belong in the suppression file with a
justification, not in code changes.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from . import Finding, Project

PASS = "rank-divergence"

SCAN_DIRS = ("examples", "horovod_tpu")
# Test worker scripts run as real ranks; the rest of tests/ drives
# them from the outside and is exempt.
SCRIPT_GLOB = "*_script.py"

RANK_CALLS = {
    "rank", "local_rank", "cross_rank", "node_rank", "process_index",
    "rank_in_process_set",
}
RANK_ATTRS = {"rank", "local_rank", "cross_rank"}

# Calls treated as collective issuance.  Matching is by terminal name
# (``hvt.allreduce`` and ``state.commit`` both match), which
# overmatches on purpose — a suppression with a justification is the
# documented way to silence a non-collective homonym.
COLLECTIVES = {
    "allreduce", "allreduce_", "grouped_allreduce", "allgather",
    "broadcast", "alltoall", "reducescatter", "barrier",
    "broadcast_object", "broadcast_variables", "broadcast_parameters",
    "broadcast_optimizer_state", "broadcast_global_variables",
    "commit", "rebroadcast",          # elastic State transactions
    "verify", "maybe_audit",          # core.audit cross-rank digests
    "aggregate",                      # obs.metrics cross-rank reduce
}
# `join` (the collective) collides with Thread.join on every worker
# thread; it is only matched through an hvt-ish receiver.
JOIN_RECEIVERS = {"hvt", "hvtpu", "horovod_tpu", "controller", "ctl"}


def _terminal_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _receiver_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return func.value.id
    return None


class _Scope:
    def __init__(self, qualname: str):
        self.qualname = qualname
        self.tainted: Set[str] = set()


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel_path: str):
        self.rel = rel_path
        self.findings: List[Finding] = []
        self.scopes: List[_Scope] = [_Scope("<module>")]
        self.rank_depth = 0  # nesting depth of rank-dependent branches

    # -- taint ---------------------------------------------------------
    def _is_rank_dependent(self, expr: ast.expr) -> bool:
        tainted = self.scopes[-1].tainted
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name in RANK_CALLS:
                    return True
            elif isinstance(node, ast.Attribute) and node.attr in RANK_ATTRS:
                return True
            elif isinstance(node, ast.Name) and node.id in tainted:
                return True
        return False

    def _note_assign(self, targets: List[ast.expr], value: Optional[ast.expr]):
        if value is None or not self._is_rank_dependent(value):
            return
        for t in targets:
            if isinstance(t, ast.Name):
                self.scopes[-1].tainted.add(t.id)

    def visit_Assign(self, node: ast.Assign):
        self._note_assign(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        self._note_assign([node.target], node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._note_assign([node.target], node.value)
        self.generic_visit(node)

    # -- scopes --------------------------------------------------------
    def _visit_scope(self, node, name: str):
        parent = self.scopes[-1].qualname
        qual = name if parent == "<module>" else f"{parent}.{name}"
        self.scopes.append(_Scope(qual))
        # A nested def is only *executed* under the enclosing branch if
        # called there; conservatively reset branch depth inside it so
        # helpers defined under `if rank==0:` don't flag their bodies.
        saved = self.rank_depth
        self.rank_depth = 0
        self.generic_visit(node)
        self.rank_depth = saved
        self.scopes.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._visit_scope(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._visit_scope(node, node.name)

    def visit_ClassDef(self, node: ast.ClassDef):
        self._visit_scope(node, node.name)

    # -- branches ------------------------------------------------------
    def _visit_branch(self, test: ast.expr, bodies: List[List[ast.stmt]]):
        dependent = self._is_rank_dependent(test)
        self.visit(test)
        if dependent:
            self.rank_depth += 1
        for body in bodies:
            for stmt in body:
                self.visit(stmt)
        if dependent:
            self.rank_depth -= 1

    def visit_If(self, node: ast.If):
        # The else/elif arm of a rank-test diverges exactly like the
        # then-arm (it runs on the complement set of ranks).
        self._visit_branch(node.test, [node.body, node.orelse])

    def visit_While(self, node: ast.While):
        self._visit_branch(node.test, [node.body, node.orelse])

    def visit_IfExp(self, node: ast.IfExp):
        self._visit_branch(node.test, [[ast.Expr(node.body)],
                                       [ast.Expr(node.orelse)]])

    # -- collectives ---------------------------------------------------
    def visit_Call(self, node: ast.Call):
        name = _terminal_name(node.func)
        is_collective = name in COLLECTIVES
        if name == "join" and _receiver_name(node.func) in JOIN_RECEIVERS:
            is_collective = True
        if is_collective and self.rank_depth > 0:
            qual = self.scopes[-1].qualname
            self.findings.append(Finding(
                PASS, self.rel, node.lineno,
                f"{self.rel}:{qual}:{name}",
                f"collective '{name}' issued under rank-dependent "
                "control flow — ranks would disagree on the op stream"))
        self.generic_visit(node)


def scan_file(project: Project, path) -> List[Finding]:
    tree = project.parse(path)
    if tree is None:
        return []
    v = _Visitor(project.rel(path))
    v.visit(tree)
    return v.findings


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    files = project.py_files(*SCAN_DIRS)
    tests_dir = project.root / "tests"
    if tests_dir.is_dir():
        files.extend(sorted(tests_dir.glob(SCRIPT_GLOB)))
    for path in files:
        findings.extend(scan_file(project, path))
    return findings
