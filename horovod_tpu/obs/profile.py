"""Device-trace op profiling: capture + summarize XLA op time.

Parity surface: the reference's per-op visibility comes from the
Timeline (``horovod/common/timeline.cc`` — tensor lifecycle phases) and
NVTX ranges for Nsight (``nvtx_op_range.cc``).  On TPU the equivalent
ground truth is the XLA device trace ``jax.profiler`` writes; this
module adds the missing half: turning the captured ``*.xplane.pb``
into a per-op-kind time table WITHOUT TensorBoard (the usual
``tensorboard_plugin_profile`` stack is protobuf-version-fragile and
absent from many images).

The xplane file is parsed with a minimal wire-format reader (~60
lines): XSpace -> planes -> lines -> events plus the event-metadata
table.  Only length-delimited fields and varints are needed.

Typical use (exactly the loop used to find that the ResNet-50 step is
34% BatchNorm column-reduces)::

    from horovod_tpu.obs import profile
    with profile.trace("/tmp/prof"):
        step(...); jax.block_until_ready(...)
    for row in profile.op_summary("/tmp/prof")[:10]:
        print(row)
"""

from __future__ import annotations

import contextlib
import glob
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

# --- minimal protobuf wire reader ------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    """Yield (field_number, wire_type, payload) over a message buffer.
    Varints yield their value encoded back as int in payload position;
    64/32-bit fields yield raw bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:          # varint
            val, pos = _read_varint(buf, pos)
            yield field, wt, val
        elif wt == 2:        # length-delimited
            ln, pos = _read_varint(buf, pos)
            yield field, wt, buf[pos:pos + ln]
            pos += ln
        elif wt == 1:        # 64-bit
            yield field, wt, buf[pos:pos + 8]
            pos += 8
        elif wt == 5:        # 32-bit
            yield field, wt, buf[pos:pos + 4]
            pos += 4
        else:  # pragma: no cover - groups unused by xplane
            raise ValueError(f"unsupported wire type {wt}")


# xplane.proto field numbers (tsl/profiler/protobuf/xplane.proto):
# XSpace.planes=1; XPlane.name=2, .lines=3, .event_metadata=4 (map;
# field 5 is the STAT metadata map — do not confuse the two);
# XLine.events=4, .name=2; XEvent.metadata_id=1, .duration_ps=3;
# XEventMetadata.id=1, .name=2, .display_name=4.


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    mid, name, display = 0, "", ""
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            mid = v
        elif f == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif f == 4 and wt == 2:
            display = v.decode("utf-8", "replace")
    return mid, (display or name)


def _parse_plane(buf: bytes):
    name = ""
    lines: List[bytes] = []
    emeta: Dict[int, str] = {}
    for f, wt, v in _fields(buf):
        if f == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif f == 3 and wt == 2:
            lines.append(v)
        elif f == 4 and wt == 2:
            # map<int64, XEventMetadata> entry: key=1, value=2
            meta_buf = None
            for mf, mwt, mv in _fields(v):
                if mf == 2 and mwt == 2:
                    meta_buf = mv
            if meta_buf is not None:
                mid, mname = _parse_event_metadata(meta_buf)
                emeta[mid] = mname
    return name, lines, emeta


def _parse_line(buf: bytes):
    name = ""
    events: List[bytes] = []
    for f, wt, v in _fields(buf):
        if f == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif f == 4 and wt == 2:
            events.append(v)
    return name, events


def _parse_event(buf: bytes) -> Tuple[int, int]:
    mid, dur_ps = 0, 0
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            mid = v
        elif f == 3 and wt == 0:
            dur_ps = v
    return mid, dur_ps


# --- public API --------------------------------------------------------


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace (thin wrapper over jax.profiler.trace so
    callers need only this module)."""
    import jax

    with jax.profiler.trace(logdir):
        yield


def _find_xplanes(logdir: str) -> List[str]:
    return sorted(
        glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                  recursive=True)
    )


def plane_names(logdir: str) -> List[str]:
    """Names of all planes in the newest trace (debugging aid)."""
    paths = _find_xplanes(logdir)
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    with open(paths[-1], "rb") as f:
        space = f.read()
    return [
        _parse_plane(v)[0]
        for f_no, wt, v in _fields(space)
        if f_no == 1 and wt == 2
    ]


def op_summary(logdir: str, *, plane_substr: str = "/device:",
               line_name: str = "XLA Ops",
               group: bool = True) -> List[dict]:
    """Aggregate per-op durations from the newest trace under logdir.

    Returns rows ``{"op": kind, "total_ms": t, "count": c}`` sorted by
    time, descending.  ``group=True`` buckets ops by fusion kind
    (multiply_reduce_fusion.123 -> multiply_reduce_fusion), which is
    the actionable granularity (e.g. BN stats reduces vs convs).
    ``plane_substr`` selects planes — the default matches the TPU/GPU
    device planes; CPU-only traces have host planes only (pass
    ``plane_substr="/host:CPU"`` with an appropriate ``line_name``).
    """
    paths = _find_xplanes(logdir)
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    with open(paths[-1], "rb") as f:
        space = f.read()

    agg: Dict[str, List[float]] = {}
    for f_no, wt, plane_buf in _fields(space):
        if f_no != 1 or wt != 2:
            continue
        pname, lines, emeta = _parse_plane(plane_buf)
        if plane_substr not in pname:
            continue
        for line_buf in lines:
            lname, events = _parse_line(line_buf)
            if lname != line_name:
                continue
            for ev in events:
                mid, dur_ps = _parse_event(ev)
                name = emeta.get(mid, f"op{mid}")
                if name.startswith("while"):
                    continue  # container; children are separate events
                if group:
                    name = re.sub(r"[.\d]+$", "", name.split(" = ")[0]
                                  .lstrip("%"))
                cell = agg.setdefault(name, [0.0, 0])
                cell[0] += dur_ps / 1e9  # ps -> ms
                cell[1] += 1
    rows = [
        {"op": k, "total_ms": round(v[0], 3), "count": v[1]}
        for k, v in agg.items()
    ]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def device_time_ms(logdir: str, **kw) -> float:
    """Total device busy time in the trace (sum over op rows)."""
    return round(sum(r["total_ms"] for r in op_summary(logdir, **kw)), 3)
