"""Subprocess execution with output piping and reliable termination.

Parity surface: ``horovod/runner/common/util/safe_shell_exec.py``
(``execute``, ``forward_stream``, GRACEFUL_TERMINATION_TIME) — fork the
worker, pump its stdout/stderr line-by-line through prefixing filters,
terminate the whole process group on failure/timeout.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

GRACEFUL_TERMINATION_TIME_S = 5.0


def term_grace_s() -> float:
    """Seconds between SIGTERM and the SIGKILL escalation when the
    launcher terminates a worker (HVTPU_TERM_GRACE_SECONDS, default 5).

    Raise it together with HVTPU_DRAIN_GRACE_SECONDS: a worker handed a
    preemption notice (core/preempt.py) needs the kill grace to at
    least cover the drain grace, or the escalation SIGKILL lands
    mid-drain-commit and downgrades a planned departure to a crash.
    Read per call so tests and long-lived drivers pick up changes."""
    raw = os.environ.get("HVTPU_TERM_GRACE_SECONDS")
    if not raw:
        return GRACEFUL_TERMINATION_TIME_S
    try:
        val = float(raw)
    except ValueError:
        return GRACEFUL_TERMINATION_TIME_S
    return val if val > 0 else GRACEFUL_TERMINATION_TIME_S


def _pump(stream, sink, prefix: str, lock: threading.Lock):
    """Forward ``stream`` to ``sink`` line-by-line with a rank prefix
    (parity: the '[1]<stdout>:' piping threads of launch_gloo)."""
    try:
        for raw in iter(stream.readline, b""):
            line = raw.decode("utf-8", errors="replace")
            with lock:
                sink.write(f"{prefix}{line}")
                sink.flush()
    finally:
        stream.close()


class WorkerProcess:
    """A launched worker with its output-pump threads."""

    def __init__(
        self,
        rank: int,
        command: Sequence[str],
        env: Dict[str, str],
        prefix_output: bool = True,
        output_dir: Optional[str] = None,
        stdout_lock: Optional[threading.Lock] = None,
    ):
        self.rank = rank
        self._files: List = []
        if output_dir is not None:
            # Parity: horovodrun --output-filename — per-rank files
            # <dir>/<rank>/{stdout,stderr}.
            rank_dir = os.path.join(output_dir, str(rank))
            os.makedirs(rank_dir, exist_ok=True)
            out_f = open(os.path.join(rank_dir, "stdout"), "wb")
            err_f = open(os.path.join(rank_dir, "stderr"), "wb")
            self._files = [out_f, err_f]
            stdout_dst, stderr_dst = out_f, err_f
            pump = False
        else:
            stdout_dst, stderr_dst = subprocess.PIPE, subprocess.PIPE
            pump = True
        self.proc = subprocess.Popen(
            list(command),
            env=env,
            stdout=stdout_dst,
            stderr=stderr_dst,
            start_new_session=True,  # own process group for clean kill
        )
        self._threads: List[threading.Thread] = []
        if pump:
            lock = stdout_lock or threading.Lock()
            p_out = f"[{rank}]<stdout>:" if prefix_output else ""
            p_err = f"[{rank}]<stderr>:" if prefix_output else ""
            for stream, sink, prefix in (
                (self.proc.stdout, sys.stdout, p_out),
                (self.proc.stderr, sys.stderr, p_err),
            ):
                t = threading.Thread(
                    target=_pump, args=(stream, sink, prefix, lock),
                    daemon=True,
                )
                t.start()
                self._threads.append(t)

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def wait(self, timeout: Optional[float] = None) -> int:
        code = self.proc.wait(timeout)
        self.join_pumps()
        return code

    def join_pumps(self):
        for t in self._threads:
            t.join(timeout=5)
        for f in self._files:
            try:
                f.close()
            except Exception:
                pass

    def terminate(self, grace_s: Optional[float] = None):
        """SIGTERM the worker's process group, escalate to SIGKILL after
        the graceful window (parity: safe_shell_exec terminate path).
        ``grace_s`` overrides HVTPU_TERM_GRACE_SECONDS for one call —
        the elastic driver passes its drain grace so a draining worker
        is never killed before its drain window expires."""
        if self.proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        if grace_s is None:
            grace_s = term_grace_s()
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.1)
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def wait_for_any_failure_or_all_done(
    workers: List[WorkerProcess],
    timeout: Optional[float] = None,
    poll_interval: float = 0.1,
    on_failure: Optional[Callable[[WorkerProcess, int], None]] = None,
) -> int:
    """Wait until every worker exits 0, or any exits non-zero (then
    terminate the rest).  Returns the overall exit code.

    Parity: the reference launcher's behavior — one failed rank takes
    the whole job down with its exit code.
    """
    deadline = None if timeout is None else time.monotonic() + timeout
    pending = {w.rank: w for w in workers}
    exit_code = 0
    failed: Optional[WorkerProcess] = None
    while pending:
        for rank in list(pending):
            w = pending[rank]
            code = w.poll()
            if code is None:
                continue
            del pending[rank]
            if code != 0 and exit_code == 0:
                exit_code = code
                failed = w
                if on_failure is not None:
                    on_failure(w, code)
        if failed is not None:
            break
        if deadline is not None and time.monotonic() > deadline:
            exit_code = 124  # shell timeout convention
            break
        if pending:
            time.sleep(poll_interval)
    for w in pending.values():
        w.terminate()
    for w in workers:
        try:
            w.proc.wait(timeout=term_grace_s() * 2)
        except subprocess.TimeoutExpired:
            pass
        w.join_pumps()
    return exit_code
