"""sim-purity pass: no host time or ambient randomness in the fabric
simulator.

The determinism/replay contract of ``horovod_tpu/sim`` (same seed ⇒
byte-identical event log, docs/simulation.md) survives only if nothing
in the package reads the host clock or the interpreter-global RNG.
This pass bans, anywhere under ``horovod_tpu/sim/``:

  * ``time.time`` / ``time.monotonic`` / ``time.sleep`` (and their
    ``_ns``/``perf_counter`` variants) — host-clock reads and real
    sleeps; simulator code must use the kernel's virtual clock
    (``SimKernel.now`` / ``.sleep`` / ``core/clock``).
  * module-level :mod:`random` functions (``random.random``,
    ``random.randint``, ``random.seed``, …) — the process-global RNG
    is shared mutable state seeded who-knows-where.  Instantiating
    ``random.Random(seed)`` is explicitly allowed: that is exactly how
    :meth:`SimKernel.rng` builds its named, seeded streams.

Both attribute calls (``time.sleep(...)``) and names bound via
``from time import sleep`` are caught.  Detection is AST-based; names
in strings/comments don't count.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from . import Finding, Project

PASS = "sim-purity"

SIM_DIR = "horovod_tpu/sim"

#: time-module attributes that read the host clock or really sleep.
BANNED_TIME = {
    "time", "time_ns", "monotonic", "monotonic_ns", "sleep",
    "perf_counter", "perf_counter_ns",
}

#: random-module attributes that are allowed (seeded generator
#: classes); every other ``random.<lowercase>`` call is the ambient
#: process-global RNG and is banned.
ALLOWED_RANDOM = {"Random", "SystemRandom"}


def _banned_random(attr: str) -> bool:
    return attr not in ALLOWED_RANDOM and not attr.startswith("_")


class _Visitor(ast.NodeVisitor):
    def __init__(self):
        # local name -> canonical "module.attr" for bindings made by
        # `from time import sleep [as s]` / `from random import randint`
        self.from_bindings: Dict[str, str] = {}
        self.hits: List[tuple] = []  # (line, canonical)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for alias in node.names:
                if alias.name in BANNED_TIME:
                    self.from_bindings[
                        alias.asname or alias.name] = f"time.{alias.name}"
        elif node.module == "random":
            for alias in node.names:
                if _banned_random(alias.name):
                    self.from_bindings[
                        alias.asname or alias.name] = f"random.{alias.name}"
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            mod, attr = fn.value.id, fn.attr
            if mod == "time" and attr in BANNED_TIME:
                self.hits.append((node.lineno, f"time.{attr}"))
            elif mod == "random" and _banned_random(attr):
                self.hits.append((node.lineno, f"random.{attr}"))
        elif isinstance(fn, ast.Name) and fn.id in self.from_bindings:
            self.hits.append((node.lineno, self.from_bindings[fn.id]))
        self.generic_visit(node)


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    files = project.py_files(SIM_DIR)
    for path in files:
        tree = project.parse(path)
        if tree is None:
            continue
        visitor = _Visitor()
        visitor.visit(tree)
        rel = project.rel(path)
        counts: Dict[str, int] = {}
        for line, canonical in visitor.hits:
            # occurrence-indexed key: stable across unrelated edits,
            # distinct when one file has several hits of one symbol
            n = counts[canonical] = counts.get(canonical, 0) + 1
            findings.append(Finding(
                PASS, rel, line,
                f"{canonical}:{path.name}:{n}",
                f"simulator code calls {canonical}() — host time / "
                "ambient randomness breaks the byte-identical replay "
                "contract; use the virtual clock (SimKernel / "
                "core.clock) or a seeded SimKernel.rng stream",
            ))
    return findings
