"""Eager mini-controller: the background cycle loop.

Parity surface: ``horovod/common/operations.cc``
(``BackgroundThreadLoop`` / ``RunLoopOnce`` / ``PerformOperation``) and
the coordination cycle of ``horovod/common/controller.cc``
(``ComputeResponseList``).  This is what lets each rank *enqueue eager
collectives in any order* — gradients materializing in different orders
across ranks — while every rank *executes* the identical agreed
sequence, which the XLA data plane (comm/eager.py) requires.

Division of labor:
- decision logic (queueing, readiness, fusion, caching, stall tracking)
  lives in the native core (``horovod_tpu.native`` — C++ when built,
  Python twin otherwise);
- this module owns the *cycle thread*, the *transport* of coordination
  blobs between ranks, and the *execution* of agreed responses on the
  XLA data plane, resolving per-op futures.

Transport: the reference gathers requests at rank 0 over MPI_Gatherv /
Gloo and broadcasts responses back.  Here the blobs ride the JAX
coordination-service KV store (``jax.distributed``) — the same service
that replaced the Gloo HTTP rendezvous — via per-cycle keys.  A
single-process world short-circuits the transport entirely.
"""

from __future__ import annotations

import base64
import itertools
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from .. import native
from ..native import wire
from ..comm import eager as eager_comm
from ..comm.compression import NoneCompressor
from ..comm.packing import pack_flat, unpack_flat
from ..comm.reduce_ops import ReduceOp
from ..core.exceptions import HorovodInternalError
from ..obs import metrics as obs_metrics

logger = logging.getLogger("horovod_tpu.eager")

# Controller telemetry (obs/metrics.py; catalog in docs/observability.md).
_M_CYCLES = obs_metrics.counter(
    "hvtpu_controller_cycles_total", "Coordination cycles run.")
_M_CYCLE_S = obs_metrics.histogram(
    "hvtpu_controller_cycle_seconds",
    "Coordination cycle duration (coalescing gate + drain + transport "
    "exchange + execution).")
_M_QUEUE_DEPTH = obs_metrics.gauge(
    "hvtpu_controller_queue_depth",
    "Ops enqueued but not yet executed, sampled after each cycle.")
_M_NEGOTIATION_S = obs_metrics.histogram(
    "hvtpu_negotiation_seconds",
    "Enqueue-to-agreed-response latency through the controller.")
_M_CACHE_HITS = obs_metrics.counter(
    "hvtpu_controller_cache_hits_total",
    "Requests answered from the response cache (name+signature only "
    "on the wire).")
_M_CACHE_SIZE = obs_metrics.gauge(
    "hvtpu_controller_cache_size", "Live response-cache entries.")

_RED_TO_WIRE = {
    ReduceOp.SUM: wire.RED_SUM,
    ReduceOp.AVERAGE: wire.RED_AVERAGE,
    ReduceOp.MIN: wire.RED_MIN,
    ReduceOp.MAX: wire.RED_MAX,
    ReduceOp.PRODUCT: wire.RED_PRODUCT,
    ReduceOp.ADASUM: wire.RED_ADASUM,
}
_WIRE_TO_RED = {v: k for k, v in _RED_TO_WIRE.items()}


def _apply_scale(t, factor: float):
    """Pre/postscale around the fused wire: one-pass Pallas scale
    kernel for floats (parity: ScaleBuffer cuda_kernels around the
    fusion buffer); int dtypes keep the legacy truncating-scale
    semantics."""
    if jnp.issubdtype(t.dtype, jnp.floating):
        from ..ops import fused_scale_cast

        return fused_scale_cast(t.reshape(-1), factor).reshape(t.shape)
    return t * jnp.asarray(factor, t.dtype)


class OpFuture:
    """Completion future for one enqueued op (parity: the handle slots of
    horovod/torch/handle_manager.cc — done flag + result/exception)."""

    def __init__(self, name: str):
        self.name = name
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def set_result(self, value):
        self._result = value
        self._event.set()

    def set_error(self, err: BaseException):
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"collective '{self.name}' did not complete in {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result


# --------------------------------------------------------------------------
# transports
# --------------------------------------------------------------------------

class TransportClosed(Exception):
    """The transport was closed while a cycle was blocked on it —
    a clean shutdown signal, not a failure."""


class LocalTransport:
    """Single-process world: coordinator == the only member."""

    def exchange(self, ctrl, cycle: int, request_blob: bytes) -> bytes:
        ctrl.ingest(request_blob)
        return ctrl.compute_responses()

    def close(self):
        pass


class KVTransport:
    """Coordination blobs over the JAX coordination-service KV store
    (replaces MPI_Gatherv/MPI_Bcast of mpi_controller.cc; the store
    itself replaces the Gloo HTTP rendezvous of http_server.py)."""

    def __init__(self, rank: int, size: int, client=None,
                 timeout_s: float = 600.0, namespace: str = "hvt_eager",
                 poll_s: float = 1.0):
        if client is None:
            from jax._src import distributed as _jd

            client = _jd.global_state.client
            if client is None:
                raise RuntimeError(
                    "KVTransport requires jax.distributed to be initialized"
                )
        self._kv = client
        self.rank = rank
        self.size = size
        self.timeout_ms = int(timeout_s * 1000)
        self.ns = namespace
        # Blocking gets are chunked into short polls so close() can
        # unblock the cycle thread promptly at shutdown (the service
        # has no cancellable get).
        self.poll_s = poll_s
        self._closed = threading.Event()
        # Raw-bytes KV API (newer jaxlib): skips base64 entirely —
        # one less encode+decode per blob and 25% less wire payload.
        self._bytes = all(
            hasattr(client, m) for m in
            ("key_value_set_bytes", "blocking_key_value_get_bytes",
             "key_value_dir_get_bytes"))
        # One directory RPC gathers every posted request blob at the
        # coordinator instead of P sequential blocking gets.
        self._dir = self._bytes or hasattr(client, "key_value_dir_get")

    def _set(self, key: str, blob: bytes):
        if self._bytes:
            self._kv.key_value_set_bytes(key, blob)
        else:
            self._kv.key_value_set(key, base64.b64encode(blob).decode())

    def _get(self, key: str) -> bytes:
        deadline = time.monotonic() + self.timeout_ms / 1000.0
        poll_ms = max(1, int(self.poll_s * 1000))
        while True:
            if self._closed.is_set():
                raise TransportClosed(key)
            try:
                if self._bytes:
                    return bytes(
                        self._kv.blocking_key_value_get_bytes(
                            key, poll_ms))
                val = self._kv.blocking_key_value_get(key, poll_ms)
                return base64.b64decode(val)
            except Exception as e:
                msg = str(e)
                if self._closed.is_set() or "CANCELLED" in msg:
                    # Service shut down under us — clean exit.
                    raise TransportClosed(key) from None
                retryable = (isinstance(e, TimeoutError)
                             or "DEADLINE_EXCEEDED" in msg
                             or "NOT_FOUND" in msg)
                if not retryable:
                    raise
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"coordination key {key!r} not posted within "
                        f"{self.timeout_ms / 1000.0:.0f}s"
                    ) from None

    def _delete(self, key: str):
        try:
            self._kv.key_value_delete(key)
        except Exception:
            pass

    def _gather_requests(self, ctrl, cycle: int):
        """Coordinator-side gather of every rank's request blob for
        this cycle: ONE directory RPC per poll returns all posted
        blobs (vs P sequential blocking gets), with the blocking-get
        path as fallback for clients without dir-get."""
        prefix = f"{self.ns}/c{cycle}/"
        if not self._dir:
            for r in range(self.size):
                ctrl.ingest(self._get(f"{prefix}r{r}"))
            return
        want = {f"{prefix}r{r}": r for r in range(self.size)}
        got: Dict[str, bytes] = {}
        deadline = time.monotonic() + self.timeout_ms / 1000.0
        sleep = 0.0
        while True:
            if self._closed.is_set():
                raise TransportClosed(prefix)
            try:
                entries = (self._kv.key_value_dir_get_bytes(prefix)
                           if self._bytes
                           else self._kv.key_value_dir_get(prefix))
            except Exception:
                entries = []
            for k, v in entries:
                if k in want and k not in got:
                    got[k] = (bytes(v) if self._bytes
                              else base64.b64decode(v))
            if len(got) == len(want):
                break
            if time.monotonic() > deadline:
                missing = sorted(
                    r for k, r in want.items() if k not in got)
                raise TimeoutError(
                    f"request blobs for cycle {cycle} not posted "
                    f"within {self.timeout_ms / 1000.0:.0f}s "
                    f"(missing ranks {missing})")
            # fast first polls for the common sub-ms skew, then back
            # off toward poll_s — a rank in a long compute step must
            # not be hammered with O(P x blob) directory re-fetches
            sleep = min(self.poll_s, sleep * 2 if sleep else 2e-4)
            time.sleep(sleep)
        # deterministic ingest order (coordinator decisions must not
        # depend on arrival order)
        for r in range(self.size):
            ctrl.ingest(got[f"{prefix}r{r}"])

    def exchange(self, ctrl, cycle: int, request_blob: bytes) -> bytes:
        req_key = f"{self.ns}/c{cycle}/r{self.rank}"
        resp_key = f"{self.ns}/c{cycle}/resp"
        self._set(req_key, request_blob)
        if self.rank == 0:
            self._gather_requests(ctrl, cycle)
            resp = ctrl.compute_responses()
            self._set(resp_key, resp)
            # GC the previous cycle's keys in ONE directory delete —
            # safe because ingesting every rank's cycle-N blob proves
            # they all consumed cycle N-1.
            if cycle > 0:
                self._delete(f"{self.ns}/c{cycle - 1}/")
            return resp
        return self._get(resp_key)

    def close(self):
        self._closed.set()


# --------------------------------------------------------------------------
# controller
# --------------------------------------------------------------------------

class _Payload:
    __slots__ = ("seq", "name", "future", "tensor", "rop", "prescale",
                 "postscale", "compressor", "splits", "kind",
                 "process_set", "psid", "root_rank", "t_enqueue")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class EagerController:
    """Cycle-loop driver around the native controller.

    One instance per process; started lazily on first async enqueue
    (parity: InitializeHorovodOnce starting BackgroundThreadLoop).
    """

    def __init__(self, rank: int, size: int, *,
                 cycle_time_ms: float = 1.0,
                 fusion_threshold: int = 64 << 20,
                 cache_capacity: int = 1024,
                 stall_warn_s: float = 60.0,
                 stall_abort_s: float = 0.0,
                 transport=None,
                 timeline=None,
                 autotuner=None,
                 process_sets: Optional[Dict[int, List[int]]] = None,
                 manual: bool = False):
        self.rank, self.size = rank, size
        # manual=True: no background thread; tests drive run_cycle_once.
        self.manual = manual
        self.cycle_time_s = cycle_time_ms / 1000.0
        self.stall_warn_s = stall_warn_s
        self.stall_abort_s = stall_abort_s
        self._ctrl = native.make_controller(
            rank, size, fusion_threshold, cache_capacity,
            stall_warn_s, stall_abort_s,
        )
        # Local mirror of process-set membership so the executor can
        # skip responses scoped to sets this rank is not part of.
        self._ps_ranks: Dict[int, List[int]] = {0: list(range(size))}
        if process_sets:
            for psid, ranks in process_sets.items():
                self._ps_ranks[psid] = sorted(ranks)
                if psid != 0:
                    self._ctrl.register_process_set(psid, list(ranks))
        self._transport = transport or (
            LocalTransport() if size == 1 else KVTransport(rank, size)
        )
        self._timeline = timeline
        self._autotuner = autotuner
        self._seq = itertools.count(1)
        self._noname: Dict[str, itertools.count] = {}
        self._group_ids = itertools.count(1)
        # Coalescing-gate state: enqueues not yet drained, and when the
        # most recent one landed (see run_cycle_once).
        self._undrained = 0
        self._last_enqueue_t = 0.0
        # RLock: grouped_enqueue holds it across validate+declare+member
        # enqueues (which lock individually) so no concurrent enqueue can
        # slip a colliding name in mid-group.
        self._lock = threading.RLock()
        self._payloads: Dict[int, _Payload] = {}
        self._by_name: Dict[str, int] = {}
        self._join_futures: List[OpFuture] = []
        self._joined_local = False
        self._cycle = 0
        self._stall_logged: set = set()
        self._stop = threading.Event()
        # Wakes the cycle loop the moment work arrives, so idle
        # backoff (see _loop) never delays a locally-enqueued op.
        self._wake = threading.Event()
        # set when a ResponseList carries shutdown=True (every rank
        # announced) — the coordinated-quiesce signal
        self._shutdown_seen = threading.Event()
        # how long stop() keeps serving peers while waiting for global
        # shutdown agreement (matches the transport's blocking-get
        # budget; a hard-crashed peer surfaces as an error there first)
        self.shutdown_linger_s = 600.0
        self._thread: Optional[threading.Thread] = None
        self._thread_error: Optional[BaseException] = None

    # ---- lifecycle ----
    def start(self):
        if self.manual:
            return
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="hvt-eager-controller", daemon=True
            )
            self._thread.start()

    def request_shutdown(self):
        """Announce this rank's shutdown in subsequent cycles WITHOUT
        stopping the cycle loop (non-blocking half of the coordinated
        shutdown; tests stopping several same-process controllers call
        this on all of them before stop() so none lingers)."""
        self._ctrl.set_shutdown()

    def stop(self):
        # Coordinated shutdown (parity: horovod_shutdown negotiating
        # DONE via the controller): announce, then KEEP CYCLING —
        # serving peers' coordination — until every rank announced
        # (the coordinator leaving early would strand ranks mid-op,
        # e.g. a process-set collective whose response is never
        # emitted).
        if (self.size > 1 and not self.manual
                and self._thread is not None and self._thread.is_alive()
                and self._thread_error is None):
            self._ctrl.set_shutdown()
            deadline = time.monotonic() + self.shutdown_linger_s
            while time.monotonic() < deadline:
                if self._shutdown_seen.wait(timeout=0.1):
                    break
                # the cycle thread dying (stall abort, transport
                # timeout) means agreement can never arrive
                if (self._thread is None or not self._thread.is_alive()
                        or self._thread_error is not None):
                    break
        self._stop.set()
        self._wake.set()
        # Close the transport so a cycle thread blocked in a
        # coordination-service get unblocks promptly (TransportClosed).
        self._transport.close()
        thread_exited = True
        if self._thread is not None:
            self._thread.join(timeout=30)
            thread_exited = not self._thread.is_alive()
            self._thread = None
        # Fail anything still outstanding, like the reference's shutdown
        # path completing callbacks with an aborted status.
        with self._lock:
            payloads = list(self._payloads.values())
            self._payloads.clear()
            self._by_name.clear()
        for p in payloads:
            p.future.set_error(
                HorovodInternalError("controller shut down with pending ops")
            )
        if thread_exited:
            self._ctrl.close()
        else:
            # The cycle thread may still be blocked in a transport call
            # holding a reference to the native controller; leaking it
            # beats a use-after-free when the call finally returns.
            logger.warning(
                "controller cycle thread did not exit within 30s; "
                "leaking native controller handle"
            )

    # ---- enqueue API ----
    def _auto_name(self, kind: str) -> str:
        # Parity: mpi_ops.py's "allreduce.noname.<n>" counters — one
        # counter PER KIND so unnamed ops of different kinds pair up
        # across ranks by per-kind issuance count even when ranks
        # interleave kinds in different orders.
        ctr = self._noname.setdefault(kind, itertools.count(0))
        return f"{kind}.noname.{next(ctr)}"

    def enqueue(self, kind: str, tensor, *, name: Optional[str] = None,
                op: ReduceOp = ReduceOp.SUM, process_set=None,
                prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                compression=NoneCompressor, root_rank: int = -1,
                splits=None, group_id: int = -1) -> OpFuture:
        if self._thread_error is not None:
            raise HorovodInternalError(
                f"controller thread died: {self._thread_error!r}"
            )
        x = jnp.asarray(tensor)
        name = name or self._auto_name(kind)
        kind_to_type = {
            "allreduce": wire.ALLREDUCE,
            "allgather": wire.ALLGATHER,
            "broadcast": wire.BROADCAST,
            "alltoall": wire.ALLTOALL,
            "reducescatter": wire.REDUCESCATTER,
            "barrier": wire.BARRIER,
        }
        op_type = kind_to_type[kind]
        compressor = compression
        # The wire dtype — what the collective actually moves — is the
        # fusion/caching signature (fusion_buffer_manager.cc keys fusion
        # on the buffer dtype).
        wire_dtype_name = str(jnp.dtype(compressor.wire_dtype(x.dtype)))
        dtype_id = wire.DTYPE_IDS.get(wire_dtype_name,
                                      wire.DTYPE_IDS.get(str(x.dtype), 6))
        psid = 0
        if process_set is not None:
            psid = (process_set if isinstance(process_set, int)
                    else process_set.process_set_id)

        fut = OpFuture(name)
        payload = _Payload(
            seq=None, name=name, future=fut, tensor=x,
            rop=op, prescale=prescale_factor, postscale=postscale_factor,
            compressor=compressor, splits=splits, kind=kind,
            process_set=process_set, psid=psid, root_rank=root_rank,
            t_enqueue=time.monotonic(),
        )
        with self._lock:
            seq = next(self._seq)
            payload.seq = seq
            ok = self._ctrl.enqueue(
                seq, name, op_type, _RED_TO_WIRE[op], dtype_id,
                tuple(int(d) for d in x.shape), psid, group_id, root_rank,
            )
            if not ok:
                fut.set_error(HorovodInternalError(
                    f"duplicate tensor name in queue: {name!r} "
                    "(parity: TensorQueue DUPLICATE_NAME_ERROR)"
                ))
                return fut
            self._payloads[seq] = payload
            self._by_name[name] = seq
            self._undrained += 1
            self._last_enqueue_t = time.monotonic()
            if self._timeline is not None:
                # Parity: timeline.cc NEGOTIATE_<OP> span from enqueue
                # until the agreed response arrives (execution phases
                # come from the data plane).  Inside the lock: the
                # cycle thread could otherwise end() before begin().
                self._timeline.begin(name, f"NEGOTIATE_{kind.upper()}")
        self._wake.set()
        self.start()
        return fut

    def grouped_enqueue(self, kind: str, tensors, names=None, **kw
                        ) -> List[OpFuture]:
        """Enqueue a set that must execute together (parity:
        hvd.grouped_allreduce via group_table.cc).

        Names are validated up front: a duplicate (within the group or
        against a pending op) fails the WHOLE group immediately, since a
        partially-enqueued group could never reach its declared quorum
        and would hang the surviving members until stall abort.
        """
        eff_names = [
            (names[i] if names else None) or self._auto_name(kind)
            for i in range(len(tensors))
        ]
        # Hold the (reentrant) lock across check + declare + enqueues so
        # a concurrent enqueue can't introduce a colliding name after
        # the check but before a member lands — that would strand a
        # partially-filled group below its declared quorum forever.
        with self._lock:
            dup = None
            seen = set()
            for n in eff_names:
                if n in seen or n in self._by_name:
                    dup = n
                    break
                seen.add(n)
            if dup is not None:
                futs = []
                for n in eff_names:
                    f = OpFuture(n)
                    f.set_error(HorovodInternalError(
                        f"duplicate tensor name in group: {dup!r} "
                        "(parity: TensorQueue DUPLICATE_NAME_ERROR)"
                    ))
                    futs.append(f)
                return futs
            gid = next(self._group_ids)
            self._ctrl.declare_group(gid, len(tensors))
            futures = [
                self.enqueue(kind, t, name=n, group_id=gid, **kw)
                for t, n in zip(tensors, eff_names)
            ]
        return futures

    def register_process_set(self, psid: int, ranks: List[int]):
        """Mirror a newly-added process set into the coordination core
        (parity: ProcessSetTable additions reaching the controller)."""
        self._ps_ranks[psid] = sorted(ranks)
        self._ctrl.register_process_set(psid, list(ranks))

    def join(self) -> OpFuture:
        """Parity: hvd.join / EnqueueJoin — resolves with the last rank
        to join once every rank has.  While joined, this rank keeps
        cycling and contributes ZEROS to collectives the remaining
        ranks run (JoinOp semantics), so uneven final batches don't
        stall them.
        """
        fut = OpFuture("join")
        with self._lock:
            self._join_futures.append(fut)
            self._joined_local = True
        self._ctrl.set_joined()
        self.start()
        return fut

    # ---- cycle loop ----
    def _loop(self):
        # Parity: BackgroundThreadLoop — run RunLoopOnce every cycle_time.
        # This thread's dispatches execute an already-negotiated
        # schedule with its own stall inspection — exempt them from the
        # sync path's pre-dispatch rendezvous (comm/stall.py).
        from ..comm import stall as sync_stall

        sync_stall.bypass_thread()
        # Idle backoff: each cycle is a full transport barrier (at
        # P>1, KV RPCs on every rank), so empty cycles are not free —
        # stretch the cadence up to 4x cycle_time while nothing is
        # happening.  A local enqueue snaps the loop awake via _wake;
        # a REMOTE rank's op waits at most the backed-off cadence
        # (bounded at 4 ms by default) for this rank's next exchange.
        idle_cycles = 0
        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                active = self.run_cycle_once()
            except TransportClosed:
                # Clean shutdown while blocked on the wire; stop() fails
                # any still-pending futures.
                break
            except BaseException as e:  # noqa: BLE001 — must fail futures
                self._thread_error = e
                logger.exception("eager controller cycle failed")
                with self._lock:
                    payloads = list(self._payloads.values())
                    self._payloads.clear()
                    self._by_name.clear()
                for p in payloads:
                    p.future.set_error(HorovodInternalError(str(e)))
                return
            if self._shutdown_seen.is_set():
                # every rank announced shutdown: global quiesce
                return
            idle_cycles = 0 if active else min(idle_cycles + 1, 3)
            elapsed = time.monotonic() - t0
            sleep = self.cycle_time_s * (1 + idle_cycles) - elapsed
            if sleep > 0:
                self._wake.wait(sleep)
            self._wake.clear()

    def run_cycle_once(self) -> bool:
        """One coordination cycle (parity: RunLoopOnce).  Returns
        True when the cycle carried work (requests drained or
        responses executed) — the loop's idle-backoff signal."""
        # Fusion-coalescing gate (the reference gets this from
        # cycle_time batching: ops enqueued within one cycle fuse into
        # one response).  While a burst of enqueues is still streaming
        # in, wait for a sub-cycle quiet gap before draining so the
        # WHOLE burst negotiates as one deterministic fusion group.
        # This matters doubly on XLA: a split burst (e.g. 6+2 instead
        # of 8) packs differently-shaped fusion buffers, and every
        # novel shape combo pays a fresh compile — measured 80-90 ms
        # spikes vs 2-4 ms steady-state for the same payload.
        # Deterministic groups keep the pack/unpack compile caches hot.
        # quiesce = one full cycle of quiet; deadline bounds the added
        # negotiation latency for a genuinely continuous stream
        t_cycle0 = time.monotonic()
        quiesce = self.cycle_time_s
        deadline = time.monotonic() + 8 * self.cycle_time_s
        while True:
            with self._lock:
                undrained = self._undrained
                last_t = self._last_enqueue_t
            now = time.monotonic()
            if (undrained == 0 or now - last_t >= quiesce
                    or now >= deadline or self._stop.is_set()):
                break
            time.sleep(min(quiesce / 2, max(deadline - now, 1e-4)))
        cycle = self._cycle
        self._cycle += 1
        if self._timeline is not None and getattr(
                self._timeline, "mark_cycles", False):
            self._timeline.mark_cycle(cycle)
        with self._lock:
            # counter reset and drain in ONE critical section: an
            # enqueue between them would be drained yet still counted,
            # making the next cycle's gate wait on a phantom op
            drained = self._undrained
            self._undrained = 0
            req = self._ctrl.drain_requests()
        if drained:
            # drained requests carry their cache-hit marks; cycles that
            # drained nothing skip the (tiny) blob re-parse entirely
            _M_CACHE_HITS.inc(
                len(wire.parse_request_list(req).cache_hits))
        resp_blob = self._transport.exchange(self._ctrl, cycle, req)
        finished = self._ctrl.apply_responses(resp_blob)
        rl = wire.parse_response_list(resp_blob)
        active = bool(rl.responses) or drained > 0
        if rl.responses or rl.join_last_rank >= 0:
            self._execute(rl, finished)
        if rl.responses and self._autotuner is not None and self.rank == 0:
            # Parity: ParameterManager.Update — the COORDINATOR scores
            # each cycle by the bytes it moved and publishes the
            # tuner's current (fusion threshold, cycle time) in the
            # next ResponseList, so every rank applies identical values
            # (per-rank tuners would diverge: scores depend on local
            # wall clock).
            self._autotuner.record_step(
                sum(rs.total_bytes for rs in rl.responses)
            )
            thr, cyc_ms = self._autotuner.current
            self._ctrl.set_tuned(int(thr), int(cyc_ms * 1000.0))
        if rl.tuned_fusion_threshold >= 0:
            self._ctrl.set_fusion_threshold(int(rl.tuned_fusion_threshold))
        if rl.tuned_cycle_time_us >= 0:
            self.cycle_time_s = rl.tuned_cycle_time_us / 1e6
        if rl.shutdown:
            self._shutdown_seen.set()
        if cycle % 256 == 0:
            self._inspect_stalls()
        _M_CYCLES.inc()
        _M_CYCLE_S.observe(time.monotonic() - t_cycle0)
        with self._lock:
            _M_QUEUE_DEPTH.set(len(self._payloads))
        cache_size = getattr(self._ctrl, "cache_size", None)
        if callable(cache_size):
            try:
                _M_CACHE_SIZE.set(cache_size())
            except Exception:
                pass
        return active

    def _inspect_stalls(self):
        # Parity: stall_inspector.cc — name the tensors and the missing
        # ranks; warn once per tensor, abort past the shutdown deadline.
        # Rank 0 (coordinator) sees per-rank presence via its message
        # table; every OTHER rank watchdogs its own pending payloads by
        # age so a stalled collective surfaces everywhere, not just on
        # the coordinator.
        if self.rank != 0:
            self._inspect_local_stalls()
            return
        for s in self._ctrl.check_stalls():
            key = s["name"]
            if key not in self._stall_logged:
                self._stall_logged.add(key)
                obs_metrics.counter("hvtpu_stall_warnings_total").inc()
                logger.warning(
                    "stalled collective %r: waited %.1fs; ranks ready %s, "
                    "ranks missing %s",
                    s["name"], s["waiting_s"], s["present"], s["missing"],
                )
            if (self.stall_abort_s > 0
                    and s["waiting_s"] > self.stall_abort_s):
                obs_metrics.counter("hvtpu_stall_aborts_total").inc()
                raise HorovodInternalError(
                    f"collective {s['name']!r} stalled for "
                    f"{s['waiting_s']:.0f}s; missing ranks {s['missing']}"
                )

    def _inspect_local_stalls(self):
        """Age-based watchdog for non-coordinator ranks: they cannot see
        which ranks are missing (only rank 0's message table can), but
        they can tell their own op has waited too long."""
        now = time.monotonic()
        with self._lock:
            pending = [(p.name, now - p.t_enqueue)
                       for p in self._payloads.values()]
        for name, waited in pending:
            if waited < self.stall_warn_s:
                continue
            key = f"local:{name}"
            if key not in self._stall_logged:
                self._stall_logged.add(key)
                obs_metrics.counter("hvtpu_stall_warnings_total").inc()
                logger.warning(
                    "stalled collective %r: waited %.1fs on rank %d "
                    "(coordinator rank 0 logs which ranks are missing)",
                    name, waited, self.rank,
                )
            if self.stall_abort_s > 0 and waited > self.stall_abort_s:
                obs_metrics.counter("hvtpu_stall_aborts_total").inc()
                raise HorovodInternalError(
                    f"collective {name!r} stalled for {waited:.0f}s on "
                    f"rank {self.rank}"
                )

    # ---- execution (parity: PerformOperation dispatching to ops/*) ----
    def _zero_payload(self, rs: wire.Response, i: int) -> _Payload:
        """Synthetic zero-contribution payload for a tensor this (joined)
        rank never enqueued (parity: JoinOp substituting a zero tensor
        so the data-plane collective still has all mesh members).

        The response's dtype is the WIRE dtype, so zeros built from it
        line up element-for-element with peers' compressed buffers.
        Allgather/alltoall contribute zero rows instead.
        """
        name = rs.tensor_names[i]
        shape = tuple(rs.tensor_shapes[i]) if i < len(rs.tensor_shapes) else ()
        dtype = jnp.dtype(wire.DTYPE_NAMES.get(rs.dtype, "float32"))
        kind_map = {
            wire.ALLREDUCE: "allreduce", wire.ALLGATHER: "allgather",
            wire.BROADCAST: "broadcast", wire.ALLTOALL: "alltoall",
            wire.REDUCESCATTER: "reducescatter", wire.BARRIER: "barrier",
        }
        kind = kind_map.get(rs.type, "allreduce")
        splits = None
        if kind in ("allgather", "alltoall"):
            shape = (0,) + shape[1:]
        if kind == "alltoall":
            members = self._ps_ranks.get(rs.process_set_id)
            p = len(members) if members else self.size
            splits = [0] * p
        fut = OpFuture(name)
        fut.set_result(None)  # nobody waits on a joined rank's result
        return _Payload(
            seq=-1, name=name, future=fut,
            tensor=jnp.zeros(shape, dtype),
            rop=_WIRE_TO_RED.get(rs.red_op, ReduceOp.SUM),
            prescale=1.0, postscale=1.0, compressor=NoneCompressor,
            splits=splits, kind=kind, process_set=rs.process_set_id,
            psid=rs.process_set_id, root_rank=rs.root_rank,
            t_enqueue=time.monotonic(),
        )

    def _take_payloads(self, rs: wire.Response,
                       strict: bool = True) -> List[_Payload]:
        """Pop this rank's payloads for a response (name + matching
        process-set id, both dicts updated together under the lock).

        ``strict=True`` (normal execution): a missing payload means a
        joined rank zero-substitutes, anything else is protocol
        corruption.  ``strict=False`` (error responses): missing
        payloads are skipped — members that never enqueued the tensor
        legitimately receive the broadcast error.
        """
        out = []
        with self._lock:
            for i, n in enumerate(rs.tensor_names):
                seq = self._by_name.get(n)
                if (seq is not None
                        and self._payloads[seq].psid == rs.process_set_id):
                    del self._by_name[n]
                    out.append(self._payloads.pop(seq))
                elif not strict:
                    continue
                elif self._joined_local:
                    out.append(self._zero_payload(rs, i))
                else:
                    raise HorovodInternalError(
                        f"response names unknown tensor {n!r} "
                        f"(process set {rs.process_set_id})"
                    )
        return out

    def _member_of(self, psid: int) -> bool:
        ranks = self._ps_ranks.get(psid)
        return ranks is None or self.rank in ranks

    def _fail_error_response(self, rs: wire.Response):
        """Fail futures for an ERROR response.  Only payloads this rank
        actually has are failed — error responses (e.g. 'rank N has
        shut down') legitimately reach member ranks that never enqueued
        the tensor, which must not be treated as protocol corruption."""
        for p in self._take_payloads(rs, strict=False):
            if self._timeline is not None:
                self._timeline.end(p.name)
            p.future.set_error(HorovodInternalError(rs.error))

    def _execute(self, rl: wire.ResponseList, finished: List[int]):
        for rs in rl.responses:
            # Responses are broadcast to every rank; only member ranks
            # of the response's process set execute it (parity: each
            # set's communicator spans exactly its members).
            if not self._member_of(rs.process_set_id):
                continue
            if rs.error:
                self._fail_error_response(rs)
                continue
            payloads = self._take_payloads(rs)
            now = time.monotonic()
            for p in payloads:
                if p.seq != -1:  # not a synthetic zero payload
                    _M_NEGOTIATION_S.observe(now - p.t_enqueue)
                    if self._timeline is not None:
                        self._timeline.end(p.name)
            try:
                self._execute_one(rs, payloads)
            except Exception as e:
                # Data-plane failure: fail exactly this response's futures
                # (parity: entry.callback(Status error) in
                # PerformOperation's error path).
                for p in payloads:
                    if not p.future.done():
                        p.future.set_error(HorovodInternalError(str(e)))
        if rl.join_last_rank >= 0:
            with self._lock:
                futs, self._join_futures = self._join_futures, []
                self._joined_local = False
            for f in futs:
                f.set_result(rl.join_last_rank)

    def _execute_one(self, rs: wire.Response, payloads: List[_Payload]):
        # Each op executes over ITS process set (parity: PerformOperation
        # looking up the Response's process_set_id communicator); the
        # controller negotiated readiness among exactly those ranks.
        if rs.type == wire.BARRIER:
            for p in payloads:
                eager_comm.barrier(process_set=p.process_set)
                p.future.set_result(None)
            return
        if rs.type == wire.ALLREDUCE:
            self._execute_allreduce(rs, payloads)
        elif rs.type == wire.ALLGATHER:
            for p in payloads:
                p.future.set_result(
                    eager_comm.allgather(p.tensor,
                                         process_set=p.process_set)
                )
        elif rs.type == wire.BROADCAST:
            for p in payloads:
                p.future.set_result(
                    eager_comm.broadcast(p.tensor, root_rank=rs.root_rank,
                                         process_set=p.process_set)
                )
        elif rs.type == wire.ALLTOALL:
            for p in payloads:
                p.future.set_result(
                    eager_comm.alltoall(p.tensor, p.splits,
                                        process_set=p.process_set)
                )
        elif rs.type == wire.REDUCESCATTER:
            for p in payloads:
                p.future.set_result(
                    eager_comm.reducescatter(p.tensor, op=p.rop,
                                             process_set=p.process_set)
                )
        else:  # pragma: no cover
            raise HorovodInternalError(f"unknown response type {rs.type}")

    def _execute_allreduce(self, rs: wire.Response, payloads: List[_Payload]):
        from ..comm.spmd import _is_int8

        rop = _WIRE_TO_RED[rs.red_op]
        unfusable = (
            rs.red_op == wire.RED_ADASUM
            # int8's per-chunk scales don't sum across ranks outside the
            # quantized-allreduce kernel; keep it on the per-tensor path
            # (subclass-aware: Int8StochasticCompressor must not slip
            # onto the fused path either).
            or any(_is_int8(p.compressor) for p in payloads)
        )
        if unfusable or len(payloads) == 1:
            # Adasum stays per-tensor (scale-invariance is per-tensor);
            # single-tensor responses skip the pack entirely.
            for p in payloads:
                out = eager_comm.allreduce(
                    p.tensor, op=p.rop,
                    prescale_factor=p.prescale,
                    postscale_factor=p.postscale,
                    compression=p.compressor,
                    name=p.name,
                    process_set=p.process_set,
                )
                p.future.set_result(out)
            return
        # Fused execution: per-tensor prescale & wire-compression commute
        # with elementwise reduction, so apply them per tensor around ONE
        # flat collective (parity: MemcpyInFusionBuffer -> single
        # ncclAllReduce -> MemcpyOutFusionBuffer).
        wires, ctxs = [], []
        for p in payloads:
            t = p.tensor
            if p.prescale != 1.0:
                t = _apply_scale(t, p.prescale)
            t, ctx = p.compressor.compress(t)
            wires.append(t)
            ctxs.append(ctx)
        flat, _ = pack_flat(wires)
        # The fuser only merges responses with equal process_set_id
        # (fallback._fuse / Controller::FuseResponses), so the group's
        # shared set is payloads[0]'s.
        red = eager_comm.allreduce(
            flat, op=rop, name=f"fused.{rs.tensor_names[0]}.{len(payloads)}",
            process_set=payloads[0].process_set,
        )
        specs = [(tuple(w.shape), w.dtype, int(w.size)) for w in wires]
        for p, ctx, piece in zip(payloads, ctxs, unpack_flat(red, specs)):
            out = p.compressor.decompress(piece, ctx)
            if p.postscale != 1.0:
                out = _apply_scale(out, p.postscale)
            p.future.set_result(out)
