"""Data-parallel MNIST-style training with the pure-JAX API.

The horovod_tpu analog of the reference's examples/pytorch/pytorch_mnist.py
training flow: init -> shard data by rank -> broadcast initial params ->
allreduce-averaged gradients each step.  Uses a synthetic MNIST-shaped
dataset so it runs hermetically (no downloads) on CPU or TPU.

Run:  hvtpurun -np 2 --cpu-devices 1 python examples/train_mnist.py
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import horovod_tpu as hvt


def make_synthetic_mnist(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 784).astype(np.float32)
    w_true = rng.randn(784, 10).astype(np.float32)
    y = (x @ w_true).argmax(axis=1)
    return x, y


def init_params(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (784, 128)) * 0.05,
        "b1": jnp.zeros((128,)),
        "w2": jax.random.normal(k2, (128, 10)) * 0.05,
        "b2": jnp.zeros((10,)),
    }


def loss_fn(params, x, y):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(logp[jnp.arange(x.shape[0]), y])


@jax.jit
def grad_step(params, x, y):
    return jax.value_and_grad(loss_fn)(params, x, y)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.5)
    p.add_argument("--train-size", type=int, default=2048)
    args = p.parse_args()

    hvt.init()
    rank, size = hvt.rank(), hvt.size()

    # Per-rank shard of the data (DistributedSampler analog).
    x, y = make_synthetic_mnist(args.train_size, seed=0)
    shard = slice(rank * len(x) // size, (rank + 1) * len(x) // size)
    x, y = x[shard], y[shard]

    # Different seeds then broadcast -> verifies param sync visibly.
    params = init_params(jax.random.PRNGKey(rank))
    params = hvt.broadcast_object(params, root_rank=0)

    steps = 0
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(len(x))
        for i in range(0, len(x) - args.batch_size + 1, args.batch_size):
            bx = jnp.asarray(x[perm[i:i + args.batch_size]])
            by = jnp.asarray(y[perm[i:i + args.batch_size]])
            loss, grads = grad_step(params, bx, by)
            flat = hvt.grouped_allreduce(
                jax.tree.leaves(grads), op=hvt.Average
            )
            grads = jax.tree.unflatten(jax.tree.structure(grads), flat)
            params = jax.tree.map(
                lambda p, g: p - args.lr * g, params, grads
            )
            steps += 1
        if rank == 0:
            print(f"epoch {epoch}: loss={float(loss):.4f}", flush=True)

    # All ranks must end bit-identical (averaged grads from identical
    # start): verify through an allgather of a param checksum.
    csum = jnp.asarray([float(jax.tree.reduce(
        lambda a, b: a + jnp.sum(b).astype(jnp.float64), params, 0.0
    ))])
    all_csums = np.asarray(hvt.allgather(csum))
    assert np.allclose(all_csums, all_csums[0]), all_csums
    if rank == 0:
        print(f"final loss {float(loss):.4f}; ranks consistent "
              f"({size} ranks, {steps} steps)", flush=True)


if __name__ == "__main__":
    main()
