"""Torch-tensor collectives over the TPU engine (parity:
horovod/torch/mpi_ops.py + the C++ binding horovod/torch/mpi_ops_v2.cc).

Adapter boundary (parity: the zero-copy ``TorchTensor``/``TorchOpContext``
adapters of adapter_v2.cc): contiguous torch tensors enter jax via
**DLPack** with no host copy (bf16 included — DLPack carries it even
though numpy can't), and results return to torch via
``torch.from_dlpack`` sharing the engine's output buffer.  Only
non-contiguous inputs and fp64 (jax x64-mode caveat) fall back to the
numpy path.  Sync ops call the engine directly; async ops flow through
the eager mini-controller (out-of-order enqueue tolerance, fusion,
response cache) and return integer handles compatible with
``synchronize``/``poll``.
"""

from __future__ import annotations

import warnings
from typing import List, Optional

import jax
import numpy as np
import torch

import horovod_tpu as _hvt

from .compression import Compression

# Re-exported reduce ops (parity: hvd.Sum/Average/Adasum/Min/Max/Product)
Sum = _hvt.Sum
Average = _hvt.Average
Adasum = _hvt.Adasum
Min = _hvt.Min
Max = _hvt.Max
Product = _hvt.Product


_TORCH_HANDLES = {}  # handle -> (payload for post-processing)


_warned_fp64 = False


def _warn_fp64():
    global _warned_fp64
    if not jax.config.jax_enable_x64 and not _warned_fp64:
        _warned_fp64 = True
        warnings.warn(
            "float64 tensor reduced without jax_enable_x64: the "
            "collective runs at float32 wire precision and the result "
            "is cast back to float64.  Set jax.config.update("
            "'jax_enable_x64', True) for true-fp64 collectives.",
            UserWarning, stacklevel=4,
        )


def _to_np(tensor: torch.Tensor) -> np.ndarray:
    """Numpy fallback path (non-contiguous/fp64/exotic layouts)."""
    t = tensor.detach()
    if not t.is_contiguous():
        t = t.contiguous()
    if t.dtype == torch.bfloat16:
        # numpy has no bf16; round-trip via fp32 (values preserved).
        return t.to(torch.float32).numpy()
    if t.dtype == torch.float64:
        _warn_fp64()
    return t.numpy()


def _to_jax(tensor: torch.Tensor):
    """torch → jax with zero host copy for contiguous CPU tensors via
    DLPack (parity: adapter_v2.cc wrapping at::Tensor storage directly;
    SURVEY.md §7.2 hard part 1).  The jax array aliases the torch
    buffer — like the reference, the caller must not mutate the tensor
    until the collective completes (synchronize for async ops)."""
    t = tensor.detach()
    if not t.is_contiguous():
        t = t.contiguous()
    if t.dtype == torch.float64:
        _warn_fp64()
        return t.numpy()  # x64 truncation semantics live in jnp.asarray
    try:
        return jax.dlpack.from_dlpack(t)
    except Exception:
        return _to_np(t)


def _from_jax(arr, like: Optional[torch.Tensor] = None) -> torch.Tensor:
    """jax → torch sharing the engine's output buffer via DLPack
    (falls back to a numpy copy when the consumer can't import it)."""
    try:
        out = torch.from_dlpack(arr)
    except Exception:
        return _from_np(np.asarray(arr), like)
    if like is not None and out.dtype != like.dtype:
        out = out.to(like.dtype)
    return out


def _from_np(arr, like: Optional[torch.Tensor] = None) -> torch.Tensor:
    a = np.ascontiguousarray(arr)
    if not a.flags.writeable:
        a = a.copy()  # jax buffers are read-only; torch wants writable
    out = torch.from_numpy(a)
    # Restore the caller's dtype: the engine computes in jax's dtype
    # system (fp64 math runs at fp32 wire precision unless
    # jax_enable_x64 is set; bf16 round-trips via fp32 since numpy has
    # no bf16).
    if like is not None and out.dtype != like.dtype:
        out = out.to(like.dtype)
    return out


def _engine_compression(compression):
    """Map torch-side Compression intent onto the engine's wire codec."""
    from ..comm.compression import Compression as EngineCompression

    if compression in (Compression.fp16,):
        return EngineCompression.fp16
    if compression in (Compression.bf16,):
        return EngineCompression.bf16
    return EngineCompression.none


# ---------------------------------------------------------------------------
# synchronous ops
#
# Gradient registration (parity: the HorovodAllreduce/HorovodAllgather/
# HorovodBroadcast/HorovodAlltoall torch.autograd.Function wrappers in
# horovod/torch/mpi_ops.py): when the input requires grad, the op
# routes through an autograd.Function whose backward implements the
# reference adjoint — grad of allreduce is an allreduce, allgather's
# grad sums and slices this rank's rows, broadcast's reduces to the
# root, alltoall's routes chunks back, reducescatter's allgathers.
# ---------------------------------------------------------------------------


def _check_grad_op(op, average):
    from ..comm.reduce_ops import ReduceOp, normalize_op

    rop = normalize_op(op, average)
    if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
        raise NotImplementedError(
            f"gradient of a {rop.name} allreduce is not defined "
            "(reference registers gradients for sum/average/adasum)")


class _AllreduceFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, average, name, compression, op,
                prescale_factor, postscale_factor, process_set):
        ctx.meta = (average, compression, op, prescale_factor,
                    postscale_factor, process_set)
        return _allreduce_impl(tensor, average, name, compression, op,
                               prescale_factor, postscale_factor,
                               process_set)

    @staticmethod
    def backward(ctx, grad):
        (average, compression, op, prescale_factor, postscale_factor,
         process_set) = ctx.meta
        _check_grad_op(op, average)
        g = allreduce(grad, average=average, compression=compression,
                      op=op, prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor,
                      process_set=process_set)
        return (g,) + (None,) * 7


def _allreduce_impl(tensor, average, name, compression, op,
                    prescale_factor, postscale_factor, process_set):
    out = _hvt.allreduce(
        _to_jax(tensor), op=op, average=average,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        compression=_engine_compression(compression),
        process_set=process_set, name=name,
    )
    return _from_jax(out, like=tensor).reshape(tensor.shape)


def allreduce(tensor: torch.Tensor, average=None, name=None,
              compression=Compression.none, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set=None) -> torch.Tensor:
    """Averaged (by default) allreduce returning a NEW tensor (parity:
    hvd.allreduce in horovod/torch/mpi_ops.py); differentiable — the
    backward pass allreduces the gradient with the same attributes."""
    if torch.is_grad_enabled() and tensor.requires_grad:
        return _AllreduceFunction.apply(
            tensor, average, name, compression, op, prescale_factor,
            postscale_factor, process_set)
    return _allreduce_impl(tensor, average, name, compression, op,
                           prescale_factor, postscale_factor,
                           process_set)


def allreduce_(tensor: torch.Tensor, average=None, name=None,
               compression=Compression.none, op=None,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0,
               process_set=None) -> torch.Tensor:
    """In-place allreduce (parity: hvd.allreduce_)."""
    result = allreduce(
        tensor, average=average, name=name, compression=compression, op=op,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    tensor.data.copy_(result)
    return tensor


class _GroupedAllreduceFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, grad_mask, average, name, compression, op,
                process_set, *tensors):
        # grad_mask rides in from the wrapper: forward receives
        # DETACHED tensors, so requires_grad must be captured outside
        ctx.meta = (grad_mask, average, compression, op, process_set)
        outs = _grouped_allreduce_impl(
            list(tensors), average, name, compression, op, process_set)
        non_diff = [o for o, m in zip(outs, grad_mask) if not m]
        if non_diff:
            # outputs of grad-free inputs must stay grad-free (a
            # mixed list would otherwise poison e.g. .numpy() on them)
            ctx.mark_non_differentiable(*non_diff)
        return tuple(outs)

    @staticmethod
    def backward(ctx, *grads):
        grad_mask, average, compression, op, process_set = ctx.meta
        _check_grad_op(op, average)
        idx = [i for i, m in enumerate(grad_mask) if m]
        gs = grouped_allreduce([grads[i] for i in idx],
                               average=average, compression=compression,
                               op=op, process_set=process_set)
        out: List[Optional[torch.Tensor]] = [None] * len(grads)
        for j, i in enumerate(idx):
            out[i] = gs[j]
        return (None,) * 6 + tuple(out)


def _grouped_allreduce_impl(tensors, average, name, compression, op,
                            process_set):
    outs = _hvt.grouped_allreduce(
        [_to_jax(t) for t in tensors], op=op, average=average,
        compression=_engine_compression(compression),
        process_set=process_set,
    )
    return [
        _from_jax(o, like=t).reshape(t.shape)
        for o, t in zip(outs, tensors)
    ]


def grouped_allreduce(tensors: List[torch.Tensor], average=None, name=None,
                      compression=Compression.none, op=None,
                      process_set=None) -> List[torch.Tensor]:
    """Fused multi-tensor allreduce (parity: hvd.grouped_allreduce);
    differentiable — the backward grouped-allreduces the gradients
    with the same attributes."""
    if torch.is_grad_enabled() and any(t.requires_grad for t in tensors):
        mask = tuple(t.requires_grad for t in tensors)
        return list(_GroupedAllreduceFunction.apply(
            mask, average, name, compression, op, process_set,
            *tensors))
    return _grouped_allreduce_impl(tensors, average, name, compression,
                                   op, process_set)


def grouped_allreduce_(tensors: List[torch.Tensor], **kw) -> List[torch.Tensor]:
    outs = grouped_allreduce(tensors, **kw)
    for t, o in zip(tensors, outs):
        t.data.copy_(o)
    return tensors


def grouped_allgather(tensors: List[torch.Tensor], name=None,
                      process_set=None) -> List[torch.Tensor]:
    """Allgather a list of tensors (parity: hvd.grouped_allgather)."""
    outs = _hvt.grouped_allgather(
        [_to_jax(t) for t in tensors], process_set=process_set
    )
    return [_from_jax(o, like=t) for o, t in zip(outs, tensors)]


def grouped_reducescatter(tensors: List[torch.Tensor], op=None,
                          process_set=None) -> List[torch.Tensor]:
    """Reducescatter a list of tensors (parity:
    hvd.grouped_reducescatter)."""
    outs = _hvt.grouped_reducescatter(
        [_to_jax(t) for t in tensors], op=op, process_set=process_set
    )
    return [_from_jax(o, like=t) for o, t in zip(outs, tensors)]


def grouped_allgather_async(tensors: List[torch.Tensor], names=None,
                            process_set=None) -> List[int]:
    handles = _hvt.grouped_allgather_async(
        [_to_jax(t) for t in tensors], names=names,
        process_set=process_set,
    )
    for h, t in zip(handles, tensors):
        _TORCH_HANDLES[h] = ("gather", t)
    return handles


def grouped_reducescatter_async(tensors: List[torch.Tensor], op=None,
                                names=None, process_set=None
                                ) -> List[int]:
    handles = _hvt.grouped_reducescatter_async(
        [_to_jax(t) for t in tensors], op=op, names=names,
        process_set=process_set,
    )
    for h, t in zip(handles, tensors):
        _TORCH_HANDLES[h] = ("gather", t)
    return handles


class _AllgatherFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, name, process_set):
        ctx.meta = (tensor.shape[0], process_set)
        return _allgather_impl(tensor, name, process_set)

    @staticmethod
    def backward(ctx, grad):
        from ..core.process_set import participant_rank

        my_rows, process_set = ctx.meta
        summed = allreduce(grad, op=_hvt.Sum, process_set=process_set)
        sizes = allgather(torch.tensor([my_rows]),
                          process_set=process_set)
        r = participant_rank(process_set)
        offset = int(sizes[:r].sum())
        return summed[offset:offset + my_rows], None, None


def _allgather_impl(tensor, name, process_set):
    out = _hvt.allgather(_to_jax(tensor), process_set=process_set,
                         name=name)
    return _from_jax(out, like=tensor)


def allgather(tensor: torch.Tensor, name=None, process_set=None
              ) -> torch.Tensor:
    """Concatenate along dim 0 across ranks (ragged dim-0 supported;
    parity: hvd.allgather / allgather size negotiation);
    differentiable — the backward sums upstream grads across ranks and
    slices out this rank's rows."""
    if torch.is_grad_enabled() and tensor.requires_grad:
        return _AllgatherFunction.apply(tensor, name, process_set)
    return _allgather_impl(tensor, name, process_set)


class _BroadcastFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, root_rank, name, process_set):
        ctx.meta = (root_rank, process_set)
        return _broadcast_impl(tensor, root_rank, name, process_set)

    @staticmethod
    def backward(ctx, grad):
        root_rank, process_set = ctx.meta
        summed = allreduce(grad, op=_hvt.Sum, process_set=process_set)
        if _hvt.rank() != root_rank:
            summed = torch.zeros_like(summed)
        return summed, None, None, None


def _broadcast_impl(tensor, root_rank, name, process_set):
    out = _hvt.broadcast(_to_jax(tensor), root_rank=root_rank,
                         process_set=process_set, name=name)
    return _from_jax(out, like=tensor).reshape(tensor.shape)


def broadcast(tensor: torch.Tensor, root_rank: int = 0, name=None,
              process_set=None) -> torch.Tensor:
    """Differentiable broadcast — gradients reduce to the root (zeros
    elsewhere), the reference's HorovodBroadcast adjoint."""
    if torch.is_grad_enabled() and tensor.requires_grad:
        return _BroadcastFunction.apply(tensor, root_rank, name,
                                        process_set)
    return _broadcast_impl(tensor, root_rank, name, process_set)


def broadcast_(tensor: torch.Tensor, root_rank: int = 0, name=None,
               process_set=None) -> torch.Tensor:
    tensor.data.copy_(broadcast(tensor, root_rank, name, process_set))
    return tensor


class _AlltoallFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, splits_np, name, process_set):
        return_single = splits_np is None
        if return_single:
            # the adjoint must replay with the RECEIVED per-sender
            # counts — ranks may contribute different row counts even
            # with equal splits — so route through the explicit-splits
            # engine path, which negotiates and returns them
            from ..core.process_set import participant_count

            p = participant_count(process_set)
            if tensor.shape[0] % p:
                raise ValueError(
                    f"alltoall dim0 {tensor.shape[0]} not divisible "
                    f"by size {p}")
            splits_np = np.full((p,), tensor.shape[0] // p, np.int32)
        data, rsplits = _alltoall_impl(tensor, splits_np, name,
                                       process_set)
        ctx.meta = (rsplits, process_set, return_single)
        if return_single:
            return data
        return data, rsplits

    @staticmethod
    def backward(ctx, grad, *grad_splits):
        rsplits, process_set, _single = ctx.meta
        g, _ = alltoall(grad, splits=rsplits, process_set=process_set)
        return g, None, None, None


def _alltoall_impl(tensor, splits_np, name, process_set):
    out = _hvt.alltoall(_to_jax(tensor), splits_np,
                        process_set=process_set, name=name)
    if isinstance(out, tuple):
        data, rsplits = out
        return (_from_jax(data, like=tensor),
                torch.as_tensor(np.asarray(rsplits)))
    return _from_jax(out, like=tensor)


def alltoall(tensor: torch.Tensor, splits: Optional[torch.Tensor] = None,
             name=None, process_set=None):
    """Scatter dim-0 slices to every rank, gather received (parity:
    hvd.alltoall; returns (output, received_splits) like the reference
    when splits is given); differentiable — the backward replays the
    exchange with the received splits."""
    if splits is None:
        splits_np = None
    elif torch.is_tensor(splits):
        splits_np = _to_np(splits)
    else:
        splits_np = np.asarray(splits, np.int32)
    if torch.is_grad_enabled() and tensor.requires_grad:
        return _AlltoallFunction.apply(tensor, splits_np, name,
                                       process_set)
    return _alltoall_impl(tensor, splits_np, name, process_set)


class _ReducescatterFunction(torch.autograd.Function):
    @staticmethod
    def forward(ctx, tensor, op, name, process_set):
        ctx.meta = (op, process_set)
        return _reducescatter_impl(tensor, op, name, process_set)

    @staticmethod
    def backward(ctx, grad):
        from ..core.process_set import participant_count
        from ..comm.reduce_ops import ReduceOp, normalize_op

        op, process_set = ctx.meta
        rop = normalize_op(op, None)
        if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            raise NotImplementedError(
                f"gradient of a {rop.name} reducescatter is not "
                "defined")
        g = allgather(grad, process_set=process_set)
        if rop == ReduceOp.AVERAGE:
            g = g / participant_count(process_set)
        return g, None, None, None


def _reducescatter_impl(tensor, op, name, process_set):
    out = _hvt.reducescatter(_to_jax(tensor), op=op,
                             process_set=process_set, name=name)
    return _from_jax(out, like=tensor)


def reducescatter(tensor: torch.Tensor, op=None, name=None,
                  process_set=None) -> torch.Tensor:
    """Differentiable reducescatter — the adjoint allgathers the shard
    gradients (averaged backward for an Average forward)."""
    if torch.is_grad_enabled() and tensor.requires_grad:
        return _ReducescatterFunction.apply(tensor, op, name,
                                            process_set)
    return _reducescatter_impl(tensor, op, name, process_set)


def barrier(process_set=None):
    _hvt.barrier(process_set=process_set)


# ---------------------------------------------------------------------------
# async ops + handle management
# ---------------------------------------------------------------------------

def allreduce_async(tensor: torch.Tensor, average=None, name=None,
                    op=None, compression=Compression.none,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set=None) -> int:
    handle = _hvt.allreduce_async(
        _to_jax(tensor), op=op, average=average, name=name,
        compression=_engine_compression(compression),
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    _TORCH_HANDLES[handle] = ("new", tensor)
    return handle


def allreduce_async_(tensor: torch.Tensor, average=None, name=None,
                     op=None, compression=Compression.none,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     process_set=None) -> int:
    """Async in-place allreduce: result lands in ``tensor`` at
    synchronize (parity: hvd.allreduce_async_)."""
    handle = _hvt.allreduce_async(
        _to_jax(tensor), op=op, average=average, name=name,
        compression=_engine_compression(compression),
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        process_set=process_set,
    )
    _TORCH_HANDLES[handle] = ("inplace", tensor)
    return handle


def grouped_allreduce_async(tensors: List[torch.Tensor], average=None,
                            names=None, op=None,
                            compression=Compression.none,
                            process_set=None) -> List[int]:
    handles = _hvt.grouped_allreduce_async(
        [_to_jax(t) for t in tensors], op=op, average=average, names=names,
        compression=_engine_compression(compression),
        process_set=process_set,
    )
    for h, t in zip(handles, tensors):
        _TORCH_HANDLES[h] = ("new", t)
    return handles


def allgather_async(tensor: torch.Tensor, name=None, process_set=None) -> int:
    handle = _hvt.allgather_async(_to_jax(tensor), name=name,
                                  process_set=process_set)
    _TORCH_HANDLES[handle] = ("gather", tensor)
    return handle


def broadcast_async(tensor: torch.Tensor, root_rank: int = 0, name=None,
                    process_set=None) -> int:
    handle = _hvt.broadcast_async(_to_jax(tensor), root_rank=root_rank,
                                  name=name, process_set=process_set)
    _TORCH_HANDLES[handle] = ("new", tensor)
    return handle


def broadcast_async_(tensor: torch.Tensor, root_rank: int = 0, name=None,
                     process_set=None) -> int:
    handle = _hvt.broadcast_async(_to_jax(tensor), root_rank=root_rank,
                                  name=name, process_set=process_set)
    _TORCH_HANDLES[handle] = ("inplace", tensor)
    return handle


def alltoall_async(tensor: torch.Tensor, splits=None, name=None,
                   process_set=None) -> int:
    splits_np = None if splits is None else _to_np(splits)
    handle = _hvt.alltoall_async(_to_jax(tensor), splits_np, name=name,
                                 process_set=process_set)
    _TORCH_HANDLES[handle] = ("gather", tensor)
    return handle


def reducescatter_async(tensor: torch.Tensor, op=None, name=None,
                        process_set=None) -> int:
    handle = _hvt.reducescatter_async(_to_jax(tensor), op=op, name=name,
                                      process_set=process_set)
    _TORCH_HANDLES[handle] = ("gather", tensor)
    return handle


class SparseAllreduceHandle:
    """Handle for a sparse allreduce: two allgathers in flight (parity:
    horovod/torch/mpi_ops.py sparse_allreduce_async's handle tuple —
    indices + values, reassembled at synchronize)."""

    def __init__(self, h_indices: int, h_values: int, shape, op, like,
                 divisor: int):
        self.h_indices = h_indices
        self.h_values = h_values
        self.shape = tuple(shape)
        self.op = op
        self.like = like
        self.divisor = divisor


_sparse_noname = iter(range(1 << 62))


from ..core.process_set import participant_count as _participant_count


def sparse_allreduce_async(tensor: torch.Tensor, name=None, op=None,
                           process_set=None) -> SparseAllreduceHandle:
    """Allreduce a ``torch.sparse_coo`` tensor (embedding gradients):
    every rank's (indices, values) are allgathered; synchronize
    reassembles and coalesces (duplicate indices sum), dividing by the
    participating rank count for Average (parity:
    sparse_allreduce_async in horovod/torch/mpi_ops.py).
    """
    if not tensor.is_sparse:
        raise ValueError("sparse_allreduce_async expects a sparse tensor")
    rop = op if op is not None else Average
    if rop not in (Sum, Average):
        raise ValueError(
            "sparse_allreduce_async supports op=Sum or Average"
        )
    t = tensor.detach().coalesce()
    name = name or f"sparse_allreduce.noname.{next(_sparse_noname)}"
    # indices: (sparse_dim, nnz) -> rows = nnz for the ragged allgather
    idx_rows = t.indices().t().contiguous()
    h_i = _hvt.allgather_async(_to_jax(idx_rows), name=f"{name}.indices",
                               process_set=process_set)
    h_v = _hvt.allgather_async(_to_jax(t.values().contiguous()),
                               name=f"{name}.values",
                               process_set=process_set)
    return SparseAllreduceHandle(
        h_i, h_v, t.shape, rop, t.values(),
        divisor=_participant_count(process_set),
    )


def _synchronize_sparse(handle: SparseAllreduceHandle) -> torch.Tensor:
    idx = _from_jax(_hvt.synchronize(handle.h_indices))
    vals = _from_jax(_hvt.synchronize(handle.h_values),
                     like=handle.like)
    if handle.op == Average:
        vals = vals / float(handle.divisor)
    out = torch.sparse_coo_tensor(
        idx.t().to(torch.int64), vals, size=handle.shape
    )
    return out.coalesce()


def synchronize(handle):
    """Wait for an async op; returns the torch result (and applies the
    in-place semantics for *_async_ variants)."""
    if isinstance(handle, SparseAllreduceHandle):
        return _synchronize_sparse(handle)
    mode, ref = _TORCH_HANDLES.pop(handle, ("new", None))
    out = _hvt.synchronize(handle)
    if isinstance(out, tuple):  # alltoall with splits
        data, rsplits = out
        return (_from_jax(data, like=ref),
                torch.as_tensor(np.asarray(rsplits)))
    if out is None:  # barrier-like
        return None
    result = _from_jax(out, like=ref)
    if mode == "inplace" and ref is not None:
        ref.data.copy_(result.reshape(ref.shape))
        return ref
    if mode == "new" and ref is not None:
        return result.reshape(ref.shape)
    return result


def poll(handle) -> bool:
    if isinstance(handle, SparseAllreduceHandle):
        return (_hvt.poll(handle.h_indices)
                and _hvt.poll(handle.h_values))
    return _hvt.poll(handle)


def join(device=None) -> int:
    return _hvt.join(device)
