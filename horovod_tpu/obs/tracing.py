"""Cross-rank distributed tracing with clock alignment.

Each rank writes a Chrome-trace JSON (through the existing
:class:`~horovod_tpu.obs.timeline.Timeline` writer) in which every
collective carries a **rank-agnostic trace id**: ``"{tensor}#{k}"``
where ``k`` counts how many times this rank has begun a span for that
tensor name.  Because the negotiation protocol (eager controller) and
the SPMD contract (sync data plane) force every member rank to process
a given tensor name the same number of times in the same order, the
same ``(name, k)`` pair on two ranks names the *same* collective —
no cross-rank coordination is needed to correlate spans.

Spans progress through the phases

    NEGOTIATE -> QUEUE -> FUSE -> EXEC  (then a DONE instant)

NEGOTIATE covers enqueue until the coordinator's response is applied,
QUEUE until the executor picks the op up, FUSE the fusion-buffer pack
window, EXEC the wire collective itself.  The sync data plane has no
negotiation, so its spans begin directly in EXEC.

Clock alignment: at install time every non-zero rank runs an NTP-style
ping/pong over the coordination KV against a responder thread on
rank 0.  For each ping, ``offset = t2 - (t1 + t4) / 2`` where t1/t4
are the peer's send/receive wall-clock times and t2 is rank 0's
wall-clock reply; the sample with the smallest round trip wins and its
error is bounded by ``rtt / 2``.  The offset (rank0-relative: adding
it to a local wall timestamp yields rank-0 time) is recorded as a
``clock_offset`` instant in the trace; ``tools/hvtputrace merge``
applies it when fusing per-rank files.

Zero-cost-when-off contract (mirrors ``core/faults.ACTIVE``): hot call
sites guard every emission with ``if tracing.ACTIVE:`` — a single
module-attribute check when ``HVTPU_TRACE`` is unset.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional

from .timeline import Timeline

logger = logging.getLogger("horovod_tpu.tracing")

# Span phases (NEGOTIATE/QUEUE deliberately match timeline.py's
# reference-parity names; FUSE/EXEC/DONE are tracing-specific).
NEGOTIATE = "NEGOTIATE"
QUEUE = "QUEUE"
FUSE = "FUSE"
EXEC = "EXEC"
DONE = "DONE"
# Predicted fast path (eager/controller._try_predict): the agreed
# schedule was reconstructed locally and execution started without
# waiting for the coordinator round trip; the span jumps
# NEGOTIATE -> PREDICT -> QUEUE and the post-hoc confirmation rides
# the request stream.
PREDICT = "PREDICT"
# Input-pipeline wait (data/loader.py): time the training loop blocked
# on the prefetch queue.  hvtputrace report buckets it separately from
# the collective wait phases so stragglers attribute to input vs
# compute vs comms.
DATA_WAIT = "DATA_WAIT"

# Module-level fast-path flag: call sites do `if tracing.ACTIVE:` so
# the disabled path is one attribute load (same contract as
# core/faults.ACTIVE, enforced by tests/test_tracing.py).
ACTIVE = False
_tracer: Optional["Tracer"] = None

_KV_NS = "hvttrace"
# Per-pong wait and total budget for the install-time clock handshake;
# on expiry tracing degrades to offset=None rather than blocking init.
_PONG_TIMEOUT_MS = 5000
_SYNC_DEADLINE_S = 30.0


class Tracer:
    """Per-rank span writer over a Timeline file in ``trace_dir``.

    Thread-safe: span bookkeeping (occurrence counters and the live-id
    map) is serialized under ``_lock``, which is also held across the
    Timeline emission so B/E ordering per tensor name is preserved even
    when phases arrive from different controller threads (enqueue
    thread -> fetcher -> executor).
    """

    def __init__(self, trace_dir: str, rank: int = 0, size: int = 1):
        self.rank = rank
        self.size = size
        os.makedirs(trace_dir, exist_ok=True)
        self.path = os.path.join(trace_dir, f"rank{rank}.trace.json")
        self._tl = Timeline(self.path, rank=rank)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}  # hvtpulint: guarded-by(_lock)
        self._live: Dict[str, str] = {}  # hvtpulint: guarded-by(_lock)
        self.offset_us: Optional[float] = 0.0 if rank == 0 else None
        self.offset_error_us: Optional[float] = 0.0 if rank == 0 else None
        self._responder: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Anchor instant: maps this file's relative timestamps (µs
        # since Timeline._t0) onto the local wall clock for the merge
        # tool.  Written first so it survives truncated traces.
        self._tl.instant(
            "clock_anchor",
            rank=rank,
            size=size,
            wall_t0_us=int(self._tl.wall_t0 * 1e6),
        )

    # ---- span API (call sites hold no locks) ----

    def op_begin(self, name: str, kind: str = "", phase: str = NEGOTIATE,
                 **args):
        with self._lock:
            k = self._counts.get(name, 0)
            self._counts[name] = k + 1
            trace_id = f"{name}#{k}"
            self._live[name] = trace_id
            self._tl.begin(name, phase, trace_id=trace_id, kind=kind, **args)

    def op_phase(self, name: str, phase: str, **args):
        with self._lock:
            trace_id = self._live.get(name)
            if trace_id is None:
                # Not a live traced op on this rank (e.g. a response for
                # a process set this rank is not a member of).
                return
            self._tl.begin(name, phase, trace_id=trace_id, **args)

    def op_done(self, name: str, **args):
        with self._lock:
            trace_id = self._live.pop(name, None)
            if trace_id is None:
                return
            self._tl.end(name)
            self._tl.instant(DONE, tensor=name, trace_id=trace_id, **args)

    # Batched variants: one lock acquisition (and one Timeline flush
    # window) for a whole fused group instead of per-op round trips —
    # the bookkeeping half of the zero-copy fusion-buffer plane.

    def op_phase_many(self, names, phase: str, **args):
        with self._lock:
            for name in names:
                trace_id = self._live.get(name)
                if trace_id is None:
                    continue
                self._tl.begin(name, phase, trace_id=trace_id, **args)

    def op_done_many(self, items, **shared):
        """``items``: iterable of ``(name, per-op-args dict)``;
        ``shared`` kwargs ride on every DONE instant."""
        with self._lock:
            for name, args in items:
                trace_id = self._live.pop(name, None)
                if trace_id is None:
                    continue
                self._tl.end(name)
                self._tl.instant(DONE, tensor=name, trace_id=trace_id,
                                 **shared, **args)

    def instant(self, name: str, **args):
        self._tl.instant(name, **args)

    # ---- clock alignment over the coordination KV ----

    def sync_clock(self, client, pings: int = 8):
        """Run the install-time clock handshake.

        rank 0 spawns a responder daemon; every other rank pings it
        ``pings`` times and keeps the minimum-RTT offset sample.  Any
        KV failure degrades to ``offset=None`` (the merge tool then
        skips offset correction for this rank) — tracing never takes
        the job down.
        """
        if client is None or self.size <= 1:
            self._emit_offset(pings_ok=0)
            return
        if self.rank == 0:
            self._responder = threading.Thread(
                target=self._respond_pings, args=(client, pings),
                name="hvtpu-trace-clock", daemon=True)
            self._responder.start()
            self._emit_offset(pings_ok=0)
            return
        best_rtt = None
        ok = 0
        try:
            for i in range(pings):
                t1 = time.time()
                client.key_value_set(
                    f"{_KV_NS}/ping/{self.rank}/{i}", repr(t1))
                t2 = float(client.blocking_key_value_get(
                    f"{_KV_NS}/pong/{self.rank}/{i}", _PONG_TIMEOUT_MS))
                t4 = time.time()
                ok += 1
                rtt = t4 - t1
                if best_rtt is None or rtt < best_rtt:
                    best_rtt = rtt
                    self.offset_us = (t2 - (t1 + t4) / 2.0) * 1e6
                    self.offset_error_us = rtt / 2.0 * 1e6
        except Exception as e:  # noqa: BLE001 - tracing must not kill init
            logger.warning("trace clock sync degraded on rank %d: %s",
                           self.rank, e)
        self._emit_offset(pings_ok=ok)

    def _respond_pings(self, client, pings: int):
        """rank-0 responder: answer each peer ping with our wall clock."""
        deadline = time.monotonic() + _SYNC_DEADLINE_S
        for i in range(pings):
            for r in range(1, self.size):
                while not self._stop.is_set():
                    try:
                        client.blocking_key_value_get(
                            f"{_KV_NS}/ping/{r}/{i}", 1000)
                    except Exception:
                        if time.monotonic() > deadline:
                            return
                        continue
                    try:
                        client.key_value_set(
                            f"{_KV_NS}/pong/{r}/{i}", repr(time.time()))
                    except Exception:
                        return
                    break

    def _emit_offset(self, pings_ok: int):
        self._tl.instant(
            "clock_offset",
            rank=self.rank,
            offset_us=self.offset_us,
            error_bound_us=self.offset_error_us,
            pings=pings_ok,
        )

    def close(self):
        self._stop.set()
        # Timeline.close() ends dangling spans itself, so an abnormal
        # shutdown still yields a parseable trace.
        self._tl.close()


# ---- module-level hot-path shims -----------------------------------------
# Call sites do `if tracing.ACTIVE: tracing.op_begin(...)`; the second
# _tracer check makes a lost race with uninstall() a no-op.

def op_begin(name: str, kind: str = "", phase: str = NEGOTIATE, **args):
    t = _tracer
    if t is not None:
        t.op_begin(name, kind, phase, **args)


def op_phase(name: str, phase: str, **args):
    t = _tracer
    if t is not None:
        t.op_phase(name, phase, **args)


def op_done(name: str, **args):
    t = _tracer
    if t is not None:
        t.op_done(name, **args)


def op_phase_many(names, phase: str, **args):
    t = _tracer
    if t is not None:
        t.op_phase_many(names, phase, **args)


def op_done_many(items, **shared):
    t = _tracer
    if t is not None:
        t.op_done_many(items, **shared)


def instant(name: str, **args):
    t = _tracer
    if t is not None:
        t.instant(name, **args)


# Step-boundary instants: the host loop (metrics.note_step via
# obs/stepprof) marks the end of each training-step window so
# ``hvtputrace overlap`` can cut the span timeline into per-step
# decompositions.  ``wall_us`` carries the local wall clock of the
# boundary — the merge tool's clock_anchor/clock_offset pipeline maps
# trace timestamps the same way, so the two stay joinable.
STEP_BOUNDARY = "step_boundary"


def step_boundary(wall_us: float, steps: float = 1.0, **args):
    t = _tracer
    if t is not None:
        t.instant(STEP_BOUNDARY, wall_us=wall_us, steps=steps, **args)


def install(trace_dir: str, rank: int = 0, size: int = 1, client=None,
            pings: int = 8) -> Tracer:
    """Create the process tracer and flip the ACTIVE fast-path flag.

    ``client`` is the coordination KV handle used for the clock
    handshake (None skips it, e.g. single-process runs and unit tests).
    """
    global _tracer, ACTIVE
    if _tracer is not None:
        uninstall()
    tracer = Tracer(trace_dir, rank=rank, size=size)
    tracer.sync_clock(client, pings=pings)
    _tracer = tracer
    ACTIVE = True
    logger.info("distributed tracing enabled: %s", tracer.path)
    return tracer


def uninstall():
    """Flush and disable tracing (idempotent; called at shutdown and
    registered via atexit through core/state so traces survive
    abnormal exits)."""
    global _tracer, ACTIVE
    ACTIVE = False
    tracer, _tracer = _tracer, None
    if tracer is not None:
        tracer.close()


def get_tracer() -> Optional[Tracer]:
    return _tracer
