"""Parameter divergence audit: prove replicas are actually identical.

Data-parallel training assumes every rank holds byte-identical
parameters and optimizer state after each step — yet nothing in the
stack *verifies* it, so a divergence (a rank that skipped a step alone,
a reset callback that rebuilt state rank-dependently, a corrupted
collective result) surfaces hours later as a loss spike with no
attribution.  This module closes that gap:

- :func:`digest_tree` hashes a pytree per rank (arrays by raw bytes +
  dtype + shape; plain leaves by ``repr``).
- :func:`verify` allgathers the digests over the coordination KV
  (through :class:`~horovod_tpu.core.retry.ResilientKV`, so transient
  coordinator blips retry with backoff) and, on mismatch, produces a
  per-tensor report naming the divergent ranks.
- :func:`maybe_audit` runs :func:`verify` every ``HVTPU_AUDIT_EVERY``
  steps (0 = disabled, the default) — the cheap periodic probe a
  training loop drops in after ``optimizer.update``.

Action on divergence (``HVTPU_AUDIT_ACTION``):

- ``abort`` (default) — raise
  :class:`~horovod_tpu.core.exceptions.HvtpuDivergenceError` (a
  :class:`HorovodInternalError` subclass), so an elastic training loop
  rolls back to the last commit and the driver relaunches the world
  from verified-identical state.
- ``warn`` — log the per-tensor report and keep going.

COLLECTIVE contract: every member rank must call :func:`verify` (or
:func:`maybe_audit` with the same step counter) the same number of
times — each call consumes a fresh per-label sequence number, exactly
like ``obs.metrics.aggregate``.  Single-process worlds degrade to a
trivially-clean local report.

Metrics: ``hvtpu_audit_runs_total`` / ``hvtpu_audit_divergences_total``
(docs/observability.md).  Knobs documented in docs/robustness.md and
plumbed through ``hvtpurun --audit-every``.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import clock
from ..obs import flight
from ..obs import metrics as obs_metrics
from .exceptions import HvtpuDivergenceError

logger = logging.getLogger("horovod_tpu")

_M_RUNS = obs_metrics.counter(
    "hvtpu_audit_runs_total",
    "Parameter divergence audits completed (clean or not).")
_M_DIVERGENCES = obs_metrics.counter(
    "hvtpu_audit_divergences_total",
    "Audits that found at least one tensor diverged across ranks.")

_NS = "hvtaudit"
_seq: Dict[Tuple[int, int, str], int] = {}
_seq_lock = threading.Lock()


def audit_every() -> int:
    """The periodic audit cadence (``HVTPU_AUDIT_EVERY``; 0 = off)."""
    try:
        return int(os.environ.get("HVTPU_AUDIT_EVERY", "0") or 0)
    except ValueError:
        raise ValueError(
            "HVTPU_AUDIT_EVERY must be an integer number of steps, got "
            f"{os.environ.get('HVTPU_AUDIT_EVERY')!r}") from None


def audit_action() -> str:
    """Divergence action (``HVTPU_AUDIT_ACTION``): abort | warn."""
    v = os.environ.get("HVTPU_AUDIT_ACTION", "abort").strip().lower()
    if v in ("", "abort"):
        return "abort"
    if v == "warn":
        return "warn"
    raise ValueError(
        f"HVTPU_AUDIT_ACTION must be 'abort' or 'warn', got {v!r}")


def _leaf_digest(leaf: Any) -> str:
    """Stable short digest of one pytree leaf.

    Arrays hash dtype + shape + raw bytes (pulled to host — the audit
    is a periodic probe, not a hot path); everything else hashes its
    ``repr``, which is stable for the scalars/strings elastic state
    tracks."""
    h = hashlib.sha256()
    if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
        import numpy as np

        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    else:
        h.update(repr(leaf).encode())
    return h.hexdigest()[:16]


def digest_tree(tree: Any) -> Dict[str, str]:
    """Per-leaf digests keyed by the jax key-path string."""
    import jax

    leaves = jax.tree_util.tree_leaves_with_path(tree)
    out = {}
    for path, leaf in leaves:
        out[jax.tree_util.keystr(path) or "<root>"] = _leaf_digest(leaf)
    return out


def _exchange(digests: Dict[str, str], label: str, st,
              timeout_s: float, client=None) -> Dict[int, Dict[str, str]]:
    """Allgather every rank's digest map over the coordination KV
    (mirrors ``obs.metrics.aggregate``'s sequence-numbered exchange).
    ``client`` injects the KV store (fabric simulator); the default is
    the process's jax coordination client."""
    from . import retry as core_retry

    if client is None:
        from jax._src import distributed as _jd

        client = _jd.global_state.client
    if client is None:
        return {st.rank: digests}
    kv = core_retry.resilient_kv(client, rank=st.rank)
    with _seq_lock:
        key = (st.init_generation, st.rank, label)
        seq = _seq.get(key, 0)
        _seq[key] = seq + 1
    prefix = f"{_NS}/{st.init_generation}/{label}/{seq}/"
    kv.key_value_set(prefix + str(st.rank), json.dumps(digests))

    per_rank: Dict[int, Dict[str, str]] = {st.rank: digests}
    deadline = clock.monotonic() + timeout_s
    for r in range(st.size):
        if r == st.rank:
            continue
        while True:
            remaining = deadline - clock.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"audit digests from rank {r} not posted within "
                    f"{timeout_s:.0f}s (label {label!r})")
            try:
                per_rank[r] = json.loads(kv.blocking_key_value_get(
                    prefix + str(r),
                    max(1, min(int(remaining * 1000), 2000))))
                break
            except Exception as e:  # not-posted-yet or transient blip
                if not core_retry.kv_blocking_retryable(e):
                    raise
    # rolling cleanup: every member posted seq, so nobody still needs
    # this rank's previous round
    if seq > 0:
        try:
            kv.key_value_delete(
                f"{_NS}/{st.init_generation}/{label}/{seq - 1}/"
                f"{st.rank}")
        except Exception:
            pass
    return per_rank


def reset_sequences() -> None:
    """Forget every (generation, rank, label) sequence counter.  The
    fabric simulator starts a fresh virtual world per run inside ONE
    process; without the reset a second run's exchange keys would
    continue the first run's sequence and never rendezvous with a
    fresh fabric."""
    with _seq_lock:
        _seq.clear()


def _find_divergence(per_rank: Dict[int, Dict[str, str]]
                     ) -> Dict[str, Dict[int, str]]:
    """Per-tensor map of rank -> digest for every tensor whose digests
    are not unanimous; a tensor MISSING on some ranks (different tree
    structure) is divergence too, reported with digest '<absent>'."""
    names: List[str] = sorted(
        {n for d in per_rank.values() for n in d})
    divergent: Dict[str, Dict[int, str]] = {}
    for n in names:
        vals = {r: per_rank[r].get(n, "<absent>")
                for r in sorted(per_rank)}
        if len(set(vals.values())) > 1:
            divergent[n] = vals
    return divergent


def _majority_outliers(vals: Dict[int, str]) -> List[int]:
    """Ranks holding a minority digest (ties: the digest of the lowest
    rank wins, so 'rank 1 diverged from rank 0', never the reverse)."""
    counts = collections.Counter(vals.values())
    best = max(counts.values())
    candidates = [d for d, c in counts.items() if c == best]
    reference = next(d for r, d in sorted(vals.items())
                     if d in candidates)
    return [r for r, d in sorted(vals.items()) if d != reference]


def format_report(label: str, divergent: Dict[str, Dict[int, str]]) -> str:
    lines = [f"parameter divergence audit [{label}]: "
             f"{len(divergent)} tensor(s) differ across ranks"]
    for n, vals in divergent.items():
        outliers = _majority_outliers(vals)
        per = ", ".join(f"rank {r}={d}" for r, d in sorted(vals.items()))
        lines.append(f"  {n}: divergent ranks {outliers} ({per})")
    return "\n".join(lines)


def verify(tree: Any, label: str = "params", *, action: Optional[str] = None,
           timeout_s: float = 60.0, client=None, world=None) -> dict:
    """Audit ``tree`` across all ranks; returns the report dict
    ``{"label", "divergent": {tensor: {rank: digest}}, "ranks": [...]}``.

    COLLECTIVE: every rank must call with the same ``label`` at the
    same point.  ``action`` overrides ``HVTPU_AUDIT_ACTION``.
    ``world`` (an object with rank/size/init_generation, treated as
    initialized) and ``client`` (the KV store) inject the exchange's
    endpoints — the fabric simulator's per-rank view; production leaves
    both None and uses the process's global state."""
    action = audit_action() if action is None else action
    if action not in ("abort", "warn"):
        raise ValueError(f"audit action must be abort|warn, got {action!r}")
    digests = digest_tree(tree)
    if world is not None:
        st, initialized = world, True
    else:
        from . import state as core_state

        st = core_state.global_state()
        initialized = st is not None and st.initialized
    if not initialized or st.size <= 1:
        per_rank = {getattr(st, "rank", 0) or 0: digests}
    else:
        per_rank = _exchange(digests, label, st, timeout_s, client=client)
    divergent = _find_divergence(per_rank)
    _M_RUNS.inc()
    report = {
        "label": label,
        "divergent": divergent,
        "ranks": sorted({r for vals in divergent.values()
                         for r in _majority_outliers(vals)}),
    }
    if flight.ACTIVE:
        flight.note("audit", label=label, action=action,
                    divergent=len(divergent), ranks=report["ranks"])
    if divergent:
        _M_DIVERGENCES.inc()
        text = format_report(label, divergent)
        if action == "abort":
            flight.dump_postmortem("divergence", label=label,
                                   ranks=report["ranks"])
            raise HvtpuDivergenceError(text)
        logger.warning("%s", text)
    return report


def maybe_audit(tree: Any, step: int, label: str = "params",
                **kw) -> Optional[dict]:
    """Run :func:`verify` when ``step`` is a multiple of
    ``HVTPU_AUDIT_EVERY`` (>0); returns the report or None when not
    due.  ``step`` must advance identically on every rank (the usual
    SPMD step counter), keeping the audit collective-safe."""
    n = audit_every()
    if n <= 0 or step % n != 0:
        return None
    return verify(tree, label=label, **kw)
