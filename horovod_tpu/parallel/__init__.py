"""horovod_tpu.parallel — hybrid-parallelism layer (dp/tp/pp/sp/ep).

The reference is data-parallel only (SURVEY.md §2.7); this package is
the TPU-first superset: mesh layouts, Megatron-style tensor parallelism,
GPipe pipeline parallelism, ring-attention and Ulysses sequence/context
parallelism for long sequences, and Switch-style expert parallelism —
all expressed as shard_map-compatible functions whose collectives XLA
lowers onto the ICI torus.
"""

from .mesh import LOGICAL_AXES, MeshLayout, auto_layout, make_layout
from .moe import expert_parallel_moe, switch_route
from .pipeline import bubble_fraction, pipeline_apply
from .ring import ring_attention
from .tp import column_parallel, row_parallel, tp_shard_dim
from .ulysses import heads_to_seq, seq_to_heads, ulysses_attention

__all__ = [
    "LOGICAL_AXES",
    "MeshLayout",
    "auto_layout",
    "make_layout",
    "ring_attention",
    "ulysses_attention",
    "seq_to_heads",
    "heads_to_seq",
    "column_parallel",
    "row_parallel",
    "tp_shard_dim",
    "pipeline_apply",
    "bubble_fraction",
    "expert_parallel_moe",
    "switch_route",
]
