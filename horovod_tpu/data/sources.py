"""Pluggable data sources for :class:`~horovod_tpu.data.ElasticDataLoader`.

A source answers exactly two questions — how many samples exist
(``len(source)``) and "materialize these global indices as a batch"
(``fetch(indices)``).  Everything elastic (sharding, cursors, resize
re-sharding) lives in the loader/sharder; sources stay dumb and
stateless so a relaunched incarnation can rebuild one from scratch and
land on byte-identical batches.

``fetch`` returns a *batch structure*: a numpy array, or a dict/tuple
of them, each with the batch as the leading dimension.  The loader
treats the structure opaquely (optionally ``device_put``-ing every
array leaf), so torch loops can consume the same sources as JAX ones.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

Batch = Union[np.ndarray, Dict[str, "Batch"], Tuple["Batch", ...]]


def map_structure(fn, struct):
    """Apply ``fn`` to every array leaf of a batch structure (dict /
    tuple / list / ndarray) — a tiny dependency-free tree map so
    sources and the loader never need jax on their import path."""
    if isinstance(struct, dict):
        return {k: map_structure(fn, v) for k, v in struct.items()}
    if isinstance(struct, (tuple, list)):
        mapped = [map_structure(fn, v) for v in struct]
        return tuple(mapped) if isinstance(struct, tuple) else mapped
    return fn(struct)


class DataSource:
    """Base source protocol: ``__len__`` + ``fetch(indices)``."""

    def __len__(self) -> int:
        raise NotImplementedError

    def fetch(self, indices: np.ndarray) -> Batch:
        """Materialize the batch for ``indices`` (global sample ids,
        possibly empty on a ragged epoch tail)."""
        raise NotImplementedError


class ArraySource(DataSource):
    """In-memory arrays (or a dict/tuple of them sharing the leading
    dimension): ``fetch`` is a fancy-index gather per leaf."""

    def __init__(self, data: Batch):
        self.data = data
        lengths = []
        map_structure(lambda a: lengths.append(len(a)), data)
        if not lengths:
            raise ValueError("ArraySource needs at least one array")
        if len(set(lengths)) != 1:
            raise ValueError(
                f"ArraySource arrays disagree on the sample dimension: "
                f"{sorted(set(lengths))}")
        self._n = lengths[0]

    def __len__(self) -> int:
        return self._n

    def fetch(self, indices: np.ndarray) -> Batch:
        return map_structure(lambda a: np.asarray(a)[indices], self.data)


class FileListSource(DataSource):
    """One sample per file path: ``fetch`` loads and stacks the
    selected files (default loader ``np.load``); an optional parallel
    ``labels`` sequence rides along as the second tuple element."""

    def __init__(self, paths: Sequence[str],
                 load_fn: Optional[Callable[[str], np.ndarray]] = None,
                 labels: Optional[Sequence] = None):
        self.paths: List[str] = list(paths)
        self.load_fn = load_fn if load_fn is not None else np.load
        self.labels = None if labels is None else np.asarray(labels)
        if self.labels is not None and len(self.labels) != len(self.paths):
            raise ValueError(
                f"labels ({len(self.labels)}) and paths "
                f"({len(self.paths)}) disagree")

    def __len__(self) -> int:
        return len(self.paths)

    def fetch(self, indices: np.ndarray) -> Batch:
        samples = [np.asarray(self.load_fn(self.paths[i]))
                   for i in indices]
        if samples:
            x = np.stack(samples)
        else:  # ragged-tail empty batch keeps a stackable shape
            x = np.empty((0,), dtype=np.float32)
        if self.labels is None:
            return x
        return (x, self.labels[indices])

    @classmethod
    def from_glob(cls, pattern: str, **kwargs) -> "FileListSource":
        import glob

        paths = sorted(glob.glob(pattern))
        if not paths:
            raise FileNotFoundError(
                f"FileListSource.from_glob: no files match {pattern!r} "
                f"(cwd {os.getcwd()})")
        return cls(paths, **kwargs)


class SyntheticSource(DataSource):
    """Deterministic index-derived samples for benchmarks and tests:
    sample ``i`` is a cheap pure function of ``i`` (a broadcast scalar
    pattern plus a modular label), so generation costs one memset-speed
    fill per batch and any two processes agree byte-for-byte without
    sharing data."""

    def __init__(self, num_samples: int, shape: Tuple[int, ...],
                 dtype=np.float32, num_classes: int = 1000,
                 seed: int = 0):
        self.num_samples = int(num_samples)
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype) if not hasattr(dtype, "itemsize") \
            else dtype
        self.num_classes = int(num_classes)
        self.seed = int(seed)

    def __len__(self) -> int:
        return self.num_samples

    def fetch(self, indices: np.ndarray) -> Batch:
        idx = np.asarray(indices, dtype=np.int64)
        # per-sample scalar in [0, 1): a fixed-point hash of (seed, i)
        mixed = (idx * 2654435761 + self.seed * 97) % 104729
        base = (mixed / 104729.0).astype(np.float32)
        x = np.broadcast_to(
            base.reshape((-1,) + (1,) * len(self.shape)),
            (len(idx),) + self.shape).astype(self.dtype)
        y = ((idx + self.seed) % self.num_classes).astype(np.int32)
        return {"x": x, "y": y}
