"""metrics-catalog fixture (bad): drift in every direction."""

from .registry import counter

# Registered but absent from docs/observability.md.
UNDOC = counter("hvtpu_fixture_undocumented_total", "Not in the catalog.")
