"""Keras callbacks (parity: horovod/keras/callbacks.py — thin classes
binding the shared impls in horovod_tpu/_keras/callbacks.py to
keras.callbacks.Callback)."""

from __future__ import annotations

import keras

from .._keras import callbacks as _impl


class BroadcastGlobalVariablesCallback(
        _impl.BroadcastGlobalVariablesCallbackImpl,
        keras.callbacks.Callback):
    """Broadcast initial model/optimizer state from ``root_rank`` so
    every rank starts identical (parity:
    hvd.callbacks.BroadcastGlobalVariablesCallback)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__(keras.backend, root_rank, device)


class MetricAverageCallback(_impl.MetricAverageCallbackImpl,
                            keras.callbacks.Callback):
    """Average epoch metrics across ranks before other callbacks see
    them (parity: hvd.callbacks.MetricAverageCallback)."""

    def __init__(self, device: str = ""):
        super().__init__(keras.backend, device)


class LearningRateWarmupCallback(_impl.LearningRateWarmupCallbackImpl,
                                 keras.callbacks.Callback):
    """Gradual LR warmup to lr×size (parity:
    hvd.callbacks.LearningRateWarmupCallback)."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch=None, verbose: int = 0,
                 initial_lr=None):
        super().__init__(keras.backend, warmup_epochs,
                         momentum_correction, steps_per_epoch, verbose,
                         initial_lr)


class LearningRateScheduleCallback(_impl.LearningRateScheduleCallbackImpl,
                                   keras.callbacks.Callback):
    """Piecewise LR schedule (parity:
    hvd.callbacks.LearningRateScheduleCallback)."""

    def __init__(self, multiplier, start_epoch: int = 0, end_epoch=None,
                 staircase: bool = True, momentum_correction: bool = True,
                 steps_per_epoch=None, initial_lr=None):
        super().__init__(keras.backend, multiplier, start_epoch,
                         end_epoch, staircase, momentum_correction,
                         steps_per_epoch, initial_lr)
