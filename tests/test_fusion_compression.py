"""Fusion bucketing + wire compression tests (parity targets:
FusionBufferManager semantics, Compression.fp16, EQuARX-style int8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.comm import Compression, ReduceOp
from horovod_tpu.comm.fusion import (
    fused_tree_allreduce,
    plan_buckets,
    plan_for_tree,
)

AXIS = "world"


def mesh8():
    return Mesh(np.asarray(jax.devices(), dtype=object), (AXIS,))


class TestBucketPlan:
    def _leaves(self, sizes):
        return [np.zeros((s,), np.float32) for s in sizes]

    def test_deterministic_sorted_order(self):
        names = ["b", "a", "c"]
        plan = plan_buckets(names, self._leaves([4, 4, 4]), 1 << 30)
        flat = [e.name for b in plan.buckets for e in b]
        assert flat == ["a", "b", "c"]

    def test_threshold_splits(self):
        # 4 tensors of 256B with a 512B threshold → 2 buckets of 2.
        names = list("abcd")
        plan = plan_buckets(names, self._leaves([64] * 4), 512)
        assert plan.num_buckets == 2
        assert all(len(b) == 2 for b in plan.buckets)

    def test_oversized_tensor_gets_own_bucket(self):
        names = ["big", "s1", "s2"]
        plan = plan_buckets(names, self._leaves([1024, 2, 2]), 128)
        sizes_per_bucket = [[e.name for e in b] for b in plan.buckets]
        assert ["big"] in sizes_per_bucket

    def test_plan_for_tree_names_are_paths(self):
        tree = {"layer1": {"w": np.zeros((2, 2), np.float32)},
                "layer0": np.zeros((3,), np.float32)}
        plan, _ = plan_for_tree(tree, 1 << 30)
        names = [e.name for b in plan.buckets for e in b]
        assert names == sorted(names)
        assert any("layer1" in n and "w" in n for n in names)


class TestFusedTreeAllreduce:
    def _run(self, tree, **kw):
        def body(t):
            return fused_tree_allreduce(
                t, axis_name=AXIS, threshold_bytes=kw.pop("threshold", 64),
                **kw,
            )

        return jax.jit(
            jax.shard_map(
                body, mesh=mesh8(), in_specs=(P(),), out_specs=P(),
                check_vma=False,
            )
        )(tree)

    def test_sum_across_replicas(self):
        tree = {"a": jnp.ones((3, 3)), "b": {"c": jnp.full((5,), 2.0)}}
        out = self._run(tree, op=ReduceOp.SUM)
        np.testing.assert_allclose(np.asarray(out["a"]), np.full((3, 3), 8.0))
        np.testing.assert_allclose(np.asarray(out["b"]["c"]), np.full((5,), 16.0))

    def test_average(self):
        tree = {"a": jnp.full((4,), 3.0)}
        out = self._run(tree, op=ReduceOp.AVERAGE)
        np.testing.assert_allclose(np.asarray(out["a"]), np.full((4,), 3.0))

    def test_mixed_dtypes_roundtrip(self):
        tree = {"w": jnp.ones((4,), jnp.bfloat16), "b": jnp.ones((2,), jnp.float32)}
        out = self._run(tree, op=ReduceOp.SUM)
        assert out["w"].dtype == jnp.bfloat16
        assert out["b"].dtype == jnp.float32

    def test_compressed_bucket(self):
        tree = {"a": jnp.full((64,), 0.125), "b": jnp.full((32,), 0.25)}
        out = self._run(tree, op=ReduceOp.SUM, compression=Compression.bf16)
        np.testing.assert_allclose(np.asarray(out["a"]), np.full((64,), 1.0))

    def test_adasum_fused(self):
        tree = {"a": jnp.ones((8,))}
        out = self._run(tree, op=ReduceOp.ADASUM)
        # identical inputs → adasum keeps the gradient
        np.testing.assert_allclose(np.asarray(out["a"]), np.ones((8,)), rtol=1e-4)


class TestCompressionRoundtrip:
    @pytest.mark.parametrize("comp,tol", [
        (Compression.fp16, 1e-3), (Compression.bf16, 1e-2),
        (Compression.int8, 2e-2),
    ])
    def test_roundtrip_error(self, comp, tol):
        rng = np.random.RandomState(11)
        x = jnp.asarray(rng.randn(1000).astype(np.float32))
        wire, ctx = comp.compress(x)
        back = comp.decompress(wire, ctx)
        assert back.dtype == x.dtype
        assert back.shape == x.shape
        err = np.abs(np.asarray(back) - np.asarray(x)).max()
        assert err < tol * np.abs(np.asarray(x)).max() + 1e-6

    def test_none_is_identity(self):
        x = jnp.arange(5.0)
        wire, ctx = Compression.none.compress(x)
        assert wire is x
        assert Compression.none.decompress(wire, ctx) is x

    def test_int_tensors_pass_through(self):
        x = jnp.arange(5, dtype=jnp.int32)
        wire, ctx = Compression.fp16.compress(x)
        assert wire.dtype == jnp.int32

    def test_int8_nonmultiple_block(self):
        x = jnp.asarray(np.random.RandomState(0).randn(1000).astype(np.float32))
        wire, ctx = Compression.int8.compress(x)
        back = Compression.int8.decompress(wire, ctx)
        assert back.shape == (1000,)

    def test_from_name(self):
        assert Compression.from_name("fp16") is Compression.fp16
        with pytest.raises(ValueError):
            Compression.from_name("zstd")
