"""Shared Keras callback implementations (parity:
horovod/_keras/callbacks.py — the concrete logic behind
horovod/keras/callbacks.py)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import horovod_tpu as _hvt


class BroadcastGlobalVariablesCallbackImpl:
    """Broadcast model + optimizer state from root_rank at the start of
    training (parity: BroadcastGlobalVariablesCallbackImpl —
    on_batch_end of batch 0, so optimizer slots exist)."""

    def __init__(self, backend, root_rank: int, device: str = "",
                 *args):
        super().__init__(*args)
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        import horovod_tpu.tensorflow as hvd_tf

        model = getattr(self, "model", None)
        if model is None:
            return
        variables = list(model.weights)
        opt = getattr(model, "optimizer", None)
        if opt is not None and hasattr(opt, "variables"):
            opt_vars = opt.variables
            variables += list(opt_vars() if callable(opt_vars)
                              else opt_vars)
        hvd_tf.broadcast_variables(variables, root_rank=self.root_rank)
        self.broadcast_done = True


class MetricAverageCallbackImpl:
    """Average epoch metrics over ranks so logs/checkpoint decisions
    agree everywhere (parity: MetricAverageCallbackImpl)."""

    def __init__(self, backend, device: str = "", *args):
        super().__init__(*args)

    def on_epoch_end(self, epoch, logs: Optional[Dict] = None):
        if not logs:
            return
        import jax.numpy as jnp

        for k, v in list(logs.items()):
            if isinstance(v, (int, float, np.floating, np.integer)):
                logs[k] = float(np.asarray(_hvt.allreduce(
                    jnp.asarray(float(v)), op=_hvt.Average,
                    name=f"metric.{k}",
                )))


class LearningRateWarmupCallbackImpl:
    """Linear LR warmup from lr to lr*size over warmup_epochs (parity:
    LearningRateWarmupCallbackImpl: 'epoch = full passes + progress';
    after warmup the multiplier stays at hvd.size())."""

    def __init__(self, backend, warmup_epochs: int = 5, momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0,
                 initial_lr: Optional[float] = None, *args):
        super().__init__(*args)
        self.warmup_epochs = warmup_epochs
        self.initial_lr = initial_lr
        self.verbose = verbose
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0

    def _lr(self):
        return self.model.optimizer.learning_rate

    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            self.initial_lr = float(np.asarray(self._lr()))

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def _epoch_progress(self, batch):
        if self.steps_per_epoch:
            return self.current_epoch + batch / self.steps_per_epoch
        return float(self.current_epoch)

    def on_batch_begin(self, batch, logs=None):
        if self.current_epoch >= self.warmup_epochs:
            mult = _hvt.size()
        else:
            progress = min(
                self._epoch_progress(batch) / max(self.warmup_epochs, 1e-9),
                1.0,
            )
            mult = 1.0 + progress * (_hvt.size() - 1)
        self.model.optimizer.learning_rate = self.initial_lr * mult

    def on_epoch_end(self, epoch, logs=None):
        if (self.verbose and epoch == self.warmup_epochs - 1
                and _hvt.rank() == 0):
            print(
                f"Epoch {epoch + 1}: finished gradual learning rate "
                f"warmup to {float(np.asarray(self._lr())):g}."
            )


class LearningRateScheduleCallbackImpl:
    """Piecewise LR schedule as a multiplier on the initial LR between
    start_epoch and end_epoch (parity:
    LearningRateScheduleCallbackImpl; multiplier may be a constant or a
    function of epoch; staircase applies it at epoch granularity)."""

    def __init__(self, backend, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 initial_lr: Optional[float] = None, *args):
        super().__init__(*args)
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = initial_lr
        self.current_epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            self.initial_lr = float(
                np.asarray(self.model.optimizer.learning_rate)
            )

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            self.model.optimizer.learning_rate = (
                self.initial_lr * self.multiplier(epoch)
            )

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_range(self.current_epoch):
            return
        if self.steps_per_epoch:
            epoch = self.current_epoch + batch / self.steps_per_epoch
        else:
            epoch = float(self.current_epoch)
        self.model.optimizer.learning_rate = (
            self.initial_lr * self.multiplier(epoch)
        )
