"""Native core tests.

Mirrors the reference's coverage of its C++ core (SURVEY.md §4: the
controller/fusion/cache logic is exercised indirectly by
test/parallel/*; we test it directly plus cross-check the C++ and
pure-Python implementations agree byte-for-byte on the wire).
"""

import json
import os

import numpy as np
import pytest

from horovod_tpu import native
from horovod_tpu.native import core as ncore
from horovod_tpu.native import fallback, wire


NATIVE = ncore.available()


def make_pair(cls, size=2, fusion=1 << 20, **kw):
    return [cls(r, size, fusion, **kw) for r in range(size)]


def run_cycle(controllers, coordinator=0):
    """One controller cycle: drain -> ingest at coordinator ->
    compute -> apply everywhere. Returns (response_blob, finished_per_rank)."""
    blobs = [c.drain_requests() for c in controllers]
    coord = controllers[coordinator]
    for b in blobs:
        coord.ingest(b)
    resp = coord.compute_responses()
    finished = [c.apply_responses(resp) for c in controllers]
    return resp, finished


CONTROLLER_IMPLS = [fallback.PyController] + (
    [ncore.NativeController] if NATIVE else []
)


@pytest.mark.parametrize("impl", CONTROLLER_IMPLS)
class TestControllerProtocol:
    def test_basic_allreduce_ready(self, impl):
        c0, c1 = make_pair(impl)
        assert c0.enqueue(1, "grad/a", wire.ALLREDUCE, wire.RED_SUM, 6, (4, 4))
        # only rank 0 has submitted -> nothing ready
        resp, fin = run_cycle([c0, c1])
        assert fin == [[], []]
        # now rank 1 submits too -> ready next cycle
        assert c1.enqueue(7, "grad/a", wire.ALLREDUCE, wire.RED_SUM, 6, (4, 4))
        resp, fin = run_cycle([c0, c1])
        rl = wire.parse_response_list(resp)
        assert len(rl.responses) == 1
        assert rl.responses[0].tensor_names == ["grad/a"]
        assert rl.responses[0].tensor_shapes == [(4, 4)]
        assert fin == [[1], [7]]

    def test_duplicate_name_rejected(self, impl):
        (c,) = make_pair(impl, size=1)
        assert c.enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (2,))
        assert not c.enqueue(2, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (2,))

    def test_fusion_under_threshold(self, impl):
        # 3 compatible f32 tensors of 10 elements = 40B each; threshold
        # 100B -> first two fuse, third goes alone (greedy, name order).
        c0, c1 = make_pair(impl, fusion=100)
        for c in (c0, c1):
            for i, name in enumerate(["a", "b", "c"]):
                c.enqueue(i + 1, name, wire.ALLREDUCE, wire.RED_SUM, 6, (10,))
        resp, fin = run_cycle([c0, c1])
        rl = wire.parse_response_list(resp)
        assert [r.tensor_names for r in rl.responses] == [["a", "b"], ["c"]]
        assert rl.responses[0].total_bytes == 80
        # finished seqs preserve response order
        assert fin[0] == [1, 2, 3]

    def test_no_fusion_across_dtype_or_op(self, impl):
        c0, c1 = make_pair(impl, fusion=1 << 20)
        for c in (c0, c1):
            c.enqueue(1, "a", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
            c.enqueue(2, "b", wire.ALLREDUCE, wire.RED_SUM, 5, (4,))  # bf16
            c.enqueue(3, "c", wire.BROADCAST, wire.RED_SUM, 6, (4,), 0, -1, 0)
        resp, _ = run_cycle([c0, c1])
        rl = wire.parse_response_list(resp)
        assert len(rl.responses) == 3

    def test_deterministic_name_order(self, impl):
        # Ranks enqueue in different orders; response order is sorted
        # by name regardless (parity: FuseResponses determinism).
        c0, c1 = make_pair(impl)
        for name, seq in (("z", 1), ("a", 2), ("m", 3)):
            c0.enqueue(seq, name, wire.ALLREDUCE, wire.RED_SUM, 6, (1000,))
        for name, seq in (("m", 1), ("z", 2), ("a", 3)):
            c1.enqueue(seq, name, wire.ALLREDUCE, wire.RED_SUM, 6, (1000,))
        resp, _ = run_cycle([c0, c1], coordinator=0)
        rl = wire.parse_response_list(resp)
        names = [n for r in rl.responses for n in r.tensor_names]
        assert names == ["a", "m", "z"]

    def test_response_cache_steady_state(self, impl):
        c0, c1 = make_pair(impl)
        for step in range(3):
            for seq, c in enumerate((c0, c1), start=step * 10):
                c.enqueue(seq + 1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (8,))
            blobs = [c.drain_requests() for c in (c0, c1)]
            parsed = [wire.parse_request_list(b) for b in blobs]
            if step == 0:
                assert not parsed[0].cache_bypass
                assert not parsed[0].requests[0].cached
            else:
                # steady state: the whole drain rides the cache-bit
                # vector (no serialized requests at all)
                for p in parsed:
                    assert p.cache_bypass
                    assert p.requests == []
                    assert wire.words_to_bits(p.cache_bits) == [0]
            for b in blobs:
                c0.ingest(b)
            resp = c0.compute_responses()
            for c in (c0, c1):
                c.apply_responses(resp)
            rl = wire.parse_response_list(resp)
            assert [r.tensor_names for r in rl.responses] == [["g"]]
            # cached expansion must preserve shape metadata
            assert rl.responses[0].tensor_shapes == [(8,)]
        assert c0.cache_size == 1

    def test_cache_eviction_consistency(self, impl):
        c0, c1 = make_pair(impl, cache_capacity=2)
        # insert 3 distinct signatures -> evicts the LRU; both ranks
        # must still agree (we just check no wrong-tensor responses).
        for step, name in enumerate(["a", "b", "c", "a", "b", "c"]):
            for c in (c0, c1):
                c.enqueue(step * 2 + c.rank + 1, name,
                          wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
            resp, fin = run_cycle([c0, c1])
            rl = wire.parse_response_list(resp)
            assert [r.tensor_names for r in rl.responses] == [[name]]
        assert c0.cache_size == 2

    def test_group_gating(self, impl):
        c0, c1 = make_pair(impl)
        for c in (c0, c1):
            c.declare_group(5, 2)
        # both ranks ready on only one of two group members -> held back
        for c in (c0, c1):
            c.enqueue(1, "g/x", wire.ALLREDUCE, wire.RED_SUM, 6, (4,), 0, 5)
        resp, fin = run_cycle([c0, c1])
        assert wire.parse_response_list(resp).responses == []
        # second member arrives -> both released together
        for c in (c0, c1):
            c.enqueue(2, "g/y", wire.ALLREDUCE, wire.RED_SUM, 6, (4,), 0, 5)
        resp, fin = run_cycle([c0, c1])
        rl = wire.parse_response_list(resp)
        names = [n for r in rl.responses for n in r.tensor_names]
        assert sorted(names) == ["g/x", "g/y"]

    def test_join(self, impl):
        c0, c1 = make_pair(impl)
        c0.set_joined()
        resp, _ = run_cycle([c0, c1])
        assert wire.parse_response_list(resp).join_last_rank == -1
        c1.set_joined()
        resp, _ = run_cycle([c0, c1])
        assert wire.parse_response_list(resp).join_last_rank == 1

    def test_process_set_subset(self, impl):
        c0, c1, c2 = make_pair(impl, size=3)
        for c in (c0, c1, c2):
            c.register_process_set(7, [0, 2])
        # only the two members of process set 7 need to report
        c0.enqueue(1, "ps", wire.ALLREDUCE, wire.RED_SUM, 6, (4,), 7)
        c2.enqueue(1, "ps", wire.ALLREDUCE, wire.RED_SUM, 6, (4,), 7)
        resp, fin = run_cycle([c0, c1, c2])
        rl = wire.parse_response_list(resp)
        assert [r.tensor_names for r in rl.responses] == [["ps"]]
        assert rl.responses[0].process_set_id == 7
        assert fin[0] == [1] and fin[1] == [] and fin[2] == [1]

    def test_stall_detection(self, impl):
        c0, c1 = make_pair(impl, stall_warn_s=0.0)
        c0.enqueue(1, "stuck", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        run_cycle([c0, c1])
        stalls = c0.check_stalls()
        assert len(stalls) == 1
        assert stalls[0]["name"] == "stuck"
        assert stalls[0]["present"] == [0]
        assert stalls[0]["missing"] == [1]

    def test_pending_introspection(self, impl):
        (c,) = make_pair(impl, size=1)
        c.enqueue(1, "t", wire.ALLREDUCE, wire.RED_SUM, 6, (10,))
        assert c.pending_count == 1
        assert c.pending_bytes == 40
        c.drain_requests()
        assert c.pending_count == 0

    def test_group_fusion_merges_non_adjacent(self, impl):
        """Compatibility-group fusion: an incompatible response landing
        between two compatible ones (table-key order a < m < z) must
        not split their fusion group."""
        c0, c1 = make_pair(impl, fusion=1 << 20)
        for c in (c0, c1):
            c.enqueue(1, "a", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
            c.enqueue(2, "m", wire.ALLREDUCE, wire.RED_SUM, 5, (4,))  # bf16
            c.enqueue(3, "z", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        resp, _ = run_cycle([c0, c1])
        rl = wire.parse_response_list(resp)
        assert [r.tensor_names for r in rl.responses] == [["a", "z"], ["m"]]

    def test_predict_responses_matches_coordinator(self, impl):
        """Steady-state schedule prediction: predict_responses(bits)
        must be byte-identical to what the coordinator computes for
        the same bypass cycle, None for unknown bits; finish() retires
        in-flight names so re-enqueues pass the duplicate guard."""
        c0, c1 = make_pair(impl, fusion=1 << 20)
        for step in range(2):
            for c in (c0, c1):
                c.enqueue(step * 4 + 1, "p/a", wire.ALLREDUCE,
                          wire.RED_SUM, 6, (8,))
                c.enqueue(step * 4 + 2, "p/b", wire.ALLREDUCE,
                          wire.RED_SUM, 6, (8,))
            run_cycle([c0, c1])
        # third (steady) cycle: predict BEFORE the exchange, then run
        # the real negotiation and compare bytes
        for c in (c0, c1):
            c.enqueue(90 + c.rank, "p/a", wire.ALLREDUCE, wire.RED_SUM,
                      6, (8,))
            c.enqueue(95 + c.rank, "p/b", wire.ALLREDUCE, wire.RED_SUM,
                      6, (8,))
        predicted = c1.predict_responses([0, 1])
        assert predicted is not None
        blobs = [c.drain_requests() for c in (c0, c1)]
        assert wire.parse_request_list(blobs[0]).cache_bypass
        for b in blobs:
            c0.ingest(b)
        real = c0.compute_responses()
        assert predicted == real
        rl = wire.parse_response_list(predicted)
        assert [r.tensor_names for r in rl.responses] == [["p/a", "p/b"]]
        assert rl.responses[0].tensor_shapes == [(8,), (8,)]
        # unknown bit: no prediction
        assert c0.predict_responses([0, 9]) is None
        # finish() retires rank 1's in-flight entries eagerly
        assert sorted(c1.finish(["p/a", "p/b"])) == [91, 96]
        assert c1.enqueue(200, "p/a", wire.ALLREDUCE, wire.RED_SUM,
                          6, (8,))

    def test_bypass_streak_and_periodic_resync(self, impl):
        """Steady state cycles between bypass blobs and the periodic
        full resync: with resync_every=4 the cadence is miss, 3×
        bypass, resync, 3× bypass, resync, ..."""
        c0, c1 = make_pair(impl, resync_every=4)
        for step in range(9):
            for c in (c0, c1):
                c.enqueue(step * 2 + c.rank + 1, "g",
                          wire.ALLREDUCE, wire.RED_SUM, 6, (8,))
            blobs = [c.drain_requests() for c in (c0, c1)]
            parsed = wire.parse_request_list(blobs[0])
            if step == 0:
                assert not parsed.cache_bypass and not parsed.cache_resync
                assert not parsed.requests[0].cached
            elif step % 4 == 0:
                # periodic resync: FULL entries (not bit-compressed),
                # flagged, hits still counted for the metrics frame
                assert parsed.cache_resync and not parsed.cache_bypass
                assert not parsed.requests[0].cached
                assert parsed.requests[0].entry.shape == (8,)
                assert parsed.cache_hits == [0]
            else:
                assert parsed.cache_bypass and not parsed.cache_resync
                assert parsed.requests == []
                assert wire.words_to_bits(parsed.cache_bits) == [0]
            for b in blobs:
                c0.ingest(b)
            resp = c0.compute_responses()
            fins = [c.apply_responses(resp) for c in (c0, c1)]
            rl = wire.parse_response_list(resp)
            assert [r.tensor_names for r in rl.responses] == [["g"]]
            assert rl.responses[0].tensor_shapes == [(8,)]
            assert fins[0] == [step * 2 + 1]

    def test_miss_exits_bypass_and_rejoins(self, impl):
        """A novel tensor mid-steady-state (miss) drops the cycle back
        to the full wire (hits bit-compressed, the miss full), then the
        next all-hit cycle resumes bypassing."""
        c0, c1 = make_pair(impl)
        seq = iter(range(1, 100))

        def cycle(names):
            for c in (c0, c1):
                for nm in names:
                    c.enqueue(next(seq), nm, wire.ALLREDUCE,
                              wire.RED_SUM, 6, (4,))
            blobs = [c.drain_requests() for c in (c0, c1)]
            for b in blobs:
                c0.ingest(b)
            resp = c0.compute_responses()
            for c in (c0, c1):
                c.apply_responses(resp)
            return wire.parse_request_list(blobs[0])

        cycle(["a"])                    # miss: full
        assert cycle(["a"]).cache_bypass        # steady state
        mixed = cycle(["a", "b"])       # miss on b: full cycle
        assert not mixed.cache_bypass
        assert mixed.requests[0].cached          # a rides its bit
        assert not mixed.requests[1].cached      # b full
        rejoin = cycle(["a", "b"])      # both hit again
        assert rejoin.cache_bypass
        assert wire.words_to_bits(rejoin.cache_bits) == [0, 1]

    def test_membership_change_forces_full_cycle(self, impl):
        c0, c1 = make_pair(impl)
        for c in (c0, c1):
            c.enqueue(1, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        run_cycle([c0, c1])
        c0.set_joined()
        c0.enqueue(2, "g", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        parsed = wire.parse_request_list(c0.drain_requests())
        # a joined/shutdown announcement must never ride a bypass blob
        assert parsed.joined and not parsed.cache_bypass

    def test_unknown_bypass_bit_requests_resync(self, impl):
        """Cache divergence recovery: an unexpandable bypass bit makes
        the coordinator broadcast cache_resync_needed (one-shot), and a
        rank applying it re-announces its in-flight ops as full
        entries so the op completes."""
        c0, c1 = make_pair(impl)
        # rank 1's op goes in flight (drained, never answered)
        c1.enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        c0.ingest(c0.drain_requests())
        c0.ingest(c1.drain_requests())
        resp = c0.compute_responses()
        for c in (c0, c1):
            c.apply_responses(resp)
        # corrupt scenario: a bypass blob referencing a bit the
        # coordinator never created
        rogue = wire.RequestList(rank=1, cache_bypass=True,
                                 cache_bits=wire.bits_to_words([7]))
        c0.ingest(wire.serialize_request_list(rogue))
        rl = wire.parse_response_list(c0.compute_responses())
        assert rl.cache_resync_needed
        # one-shot: the next ResponseList is clean
        rl2 = wire.parse_response_list(c0.compute_responses())
        assert not rl2.cache_resync_needed
        # a rank that applies the resync response re-announces its
        # in-flight op with the FULL entry
        c1.apply_responses(wire.serialize_response_list(
            wire.ResponseList(cache_resync_needed=True)))
        parsed = wire.parse_request_list(c1.drain_requests())
        assert parsed.cache_resync
        assert [rq.entry.name for rq in parsed.requests] == ["x"]
        assert not parsed.requests[0].cached
        assert parsed.requests[0].entry.shape == (4,)
        # the re-announcement completes the op once rank 0 reports too
        c0.enqueue(2, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (4,))
        resp, fins = run_cycle([c0, c1])
        names = [n for r in wire.parse_response_list(resp).responses
                 for n in r.tensor_names]
        assert names == ["x"]
        assert fins[1] == [1]


class TestCacheBitsWire:
    """The v3 cache_bits frame: packing helpers + blob round-trips."""

    def test_bits_words_roundtrip(self):
        for bits in ([], [0], [63], [64], [0, 1, 63, 64, 65, 127, 128],
                     [5, 200, 1023]):
            words = wire.bits_to_words(bits)
            assert wire.words_to_bits(words) == sorted(bits)

    def test_request_list_bypass_roundtrip(self):
        rl = wire.RequestList(rank=3, cache_bypass=True,
                              cache_bits=wire.bits_to_words([0, 2, 70]))
        out = wire.parse_request_list(wire.serialize_request_list(rl))
        assert out.rank == 3
        assert out.cache_bypass and not out.cache_resync
        assert out.requests == []
        assert wire.words_to_bits(out.cache_bits) == [0, 2, 70]

    def test_request_list_resync_flag_roundtrip(self):
        rl = wire.RequestList(rank=1, cache_resync=True)
        out = wire.parse_request_list(wire.serialize_request_list(rl))
        assert out.cache_resync and not out.cache_bypass

    def test_response_list_resync_needed_roundtrip(self):
        rl = wire.ResponseList(cache_resync_needed=True)
        out = wire.parse_response_list(wire.serialize_response_list(rl))
        assert out.cache_resync_needed

    def test_bypass_blob_is_much_smaller(self):
        """The point of the fast path: a steady-state drain of many ops
        is a handful of bytes, not O(requests)."""
        full = wire.RequestList(rank=0)
        for i in range(32):
            full.requests.append(wire.Request(rank=0, entry=wire.Entry(
                seq=i, name=f"grad/layer{i}/kernel", dtype=6,
                shape=(128, 128))))
        bypass = wire.RequestList(
            rank=0, cache_bypass=True,
            cache_bits=wire.bits_to_words(list(range(32))))
        nfull = len(wire.serialize_request_list(full))
        nbyp = len(wire.serialize_request_list(bypass))
        assert nbyp < nfull / 20
        assert nbyp < 48  # v5: +8 bytes of burst-unit delimiter


@pytest.mark.skipif(not NATIVE, reason="no C++ toolchain")
class TestNativePythonAgreement:
    """The native and Python controllers must emit identical bytes for
    identical inputs — that is what allows mixed fleets."""

    def test_wire_bytes_identical(self):
        seq_ops = [
            (1, "w/dense/kernel", wire.ALLREDUCE, wire.RED_AVERAGE, 6, (128, 64)),
            (2, "w/dense/bias", wire.ALLREDUCE, wire.RED_AVERAGE, 6, (64,)),
            (3, "bcast/step", wire.BROADCAST, wire.RED_SUM, 3, ()),
        ]
        for step in range(3):  # includes cache steady-state cycles
            nat = make_pair(ncore.NativeController, size=2, fusion=1 << 10)
            py = make_pair(fallback.PyController, size=2, fusion=1 << 10)
            for _ in range(step + 1):
                for c in nat + py:
                    for seq, name, op, red, dt, shape in seq_ops:
                        c.enqueue(seq, name, op, red, dt, shape,
                                  0, -1, 0 if op == wire.BROADCAST else -1)
                nat_blobs = [c.drain_requests() for c in nat]
                py_blobs = [c.drain_requests() for c in py]
                assert nat_blobs == py_blobs
                for b in nat_blobs:
                    nat[0].ingest(b)
                for b in py_blobs:
                    py[0].ingest(b)
                nat_resp = nat[0].compute_responses()
                py_resp = py[0].compute_responses()
                assert nat_resp == py_resp
                nat_fins = [c.apply_responses(nat_resp) for c in nat]
                py_fins = [c.apply_responses(py_resp) for c in py]
                assert nat_fins == py_fins

    def test_predicted_confirmation_bytes_identical(self):
        """v5 acceptance: a fully-predicted steady cycle — every rank
        posts a `predicted` bypass confirmation — suppresses to a
        confirm hash instead of a response stream, and the native and
        Python coordinators must emit byte-identical ResponseLists
        whose hash equals the FNV-1a64 of the predicted bytes."""
        nat = make_pair(ncore.NativeController, size=2, fusion=1 << 20)
        py = make_pair(fallback.PyController, size=2, fusion=1 << 20)

        def cycle(pairs, seq0):
            for pair in pairs:
                for c in pair:
                    c.enqueue(seq0 + c.rank, "pc/a", wire.ALLREDUCE,
                              wire.RED_SUM, 6, (8,))
                    c.enqueue(seq0 + 10 + c.rank, "pc/b", wire.ALLREDUCE,
                              wire.RED_SUM, 6, (8,))
            nat_blobs = [c.drain_requests() for c in pairs[0]]
            py_blobs = [c.drain_requests() for c in pairs[1]]
            assert nat_blobs == py_blobs
            return nat_blobs, py_blobs

        # two warm-up cycles establish the cache; cycle 3 is steady
        for step in range(2):
            nat_blobs, py_blobs = cycle((nat, py), step * 100 + 1)
            for b in nat_blobs:
                nat[0].ingest(b)
            for b in py_blobs:
                py[0].ingest(b)
            nat_resp = nat[0].compute_responses()
            py_resp = py[0].compute_responses()
            assert nat_resp == py_resp
            for c in nat:
                c.apply_responses(nat_resp)
            for c in py:
                c.apply_responses(py_resp)

        # steady cycle: every rank predicts locally, then posts its
        # drained bypass blob with the predicted flag set (the compact
        # post-hoc confirmation) instead of waiting for responses
        for pair in (nat, py):
            for c in pair:
                c.enqueue(300 + c.rank, "pc/a", wire.ALLREDUCE,
                          wire.RED_SUM, 6, (8,))
                c.enqueue(310 + c.rank, "pc/b", wire.ALLREDUCE,
                          wire.RED_SUM, 6, (8,))
        nat_pred = [c.predict_responses([0, 1]) for c in nat]
        py_pred = [c.predict_responses([0, 1]) for c in py]
        assert nat_pred[0] is not None
        assert nat_pred == py_pred
        nat_blobs = [wire.mark_predicted(c.drain_requests()) for c in nat]
        py_blobs = [wire.mark_predicted(c.drain_requests()) for c in py]
        assert nat_blobs == py_blobs
        parsed = wire.parse_request_list(py_blobs[0])
        assert parsed.predicted and parsed.cache_bypass
        assert parsed.burst_len == 2 and parsed.burst_id > 0
        for b in nat_blobs:
            nat[0].ingest(b)
        for b in py_blobs:
            py[0].ingest(b)
        nat_resp = nat[0].compute_responses()
        py_resp = py[0].compute_responses()
        assert nat_resp == py_resp
        rl = wire.parse_response_list(py_resp)
        assert rl.responses == []  # suppressed: nobody needs the bytes
        assert rl.confirm_hashes == [wire.fnv1a64(py_pred[0])]
        # force_resync (mispredict re-anchor) agrees byte-for-byte too:
        # the next drain is a full-entry resync frame in both impls
        for pair in (nat, py):
            for c in pair:
                c.force_resync()
                c.enqueue(400 + c.rank, "pc/a", wire.ALLREDUCE,
                          wire.RED_SUM, 6, (8,))
        nat_blobs = [c.drain_requests() for c in nat]
        py_blobs = [c.drain_requests() for c in py]
        assert nat_blobs == py_blobs
        parsed = wire.parse_request_list(py_blobs[0])
        assert parsed.cache_resync and not parsed.cache_bypass
        assert parsed.requests[0].entry.shape == (8,)

    def test_join_semantics_bytes_identical(self):
        """Joined-rank implicit readiness, the per-set table keys, and
        the joined-zero-contribution error texts must agree between the
        C++ and Python controllers byte-for-byte."""
        nat = make_pair(ncore.NativeController, size=2, fusion=1 << 10)
        py = make_pair(fallback.PyController, size=2, fusion=1 << 10)
        for pair in (nat, py):
            pair[1].set_joined()
            # rank 0 alone: sum unlocks via implicit readiness, min and
            # int8 produce error responses, broadcast from joined root 1
            # errors too.
            pair[0].enqueue(1, "ok_sum", wire.ALLREDUCE, wire.RED_SUM,
                            6, (4,))
            pair[0].enqueue(2, "bad_min", wire.ALLREDUCE, wire.RED_MIN,
                            6, (4,))
            pair[0].enqueue(3, "bad_int8", wire.ALLREDUCE, wire.RED_SUM,
                            1, (4,))
            pair[0].enqueue(4, "bad_root", wire.BROADCAST, wire.RED_SUM,
                            6, (4,), 0, -1, 1)
        nat_blobs = [c.drain_requests() for c in nat]
        py_blobs = [c.drain_requests() for c in py]
        assert nat_blobs == py_blobs
        for b in nat_blobs:
            nat[0].ingest(b)
        for b in py_blobs:
            py[0].ingest(b)
        nat_resp = nat[0].compute_responses()
        py_resp = py[0].compute_responses()
        assert nat_resp == py_resp
        rl = wire.parse_response_list(py_resp)
        by_name = {rs.tensor_names[0]: rs for rs in rl.responses}
        assert by_name["ok_sum"].error == ""
        assert "does not support joined-rank" in by_name["bad_min"].error
        assert "int8 wire format" in by_name["bad_int8"].error
        assert by_name["bad_root"].error == "broadcast root rank 1 has joined"

    def test_per_process_set_table_keys_bytes_identical(self):
        """Same tensor name in two disjoint sets -> two responses; C++
        and Python must order and serialize them identically."""
        nat = make_pair(ncore.NativeController, size=4, fusion=1 << 10)
        py = make_pair(fallback.PyController, size=4, fusion=1 << 10)
        for pair in (nat, py):
            for c in pair:
                c.register_process_set(1, [0, 2])
                c.register_process_set(2, [1, 3])
            pair[0].enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (2,), 1)
            pair[2].enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (2,), 1)
            pair[1].enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (5,), 2)
            pair[3].enqueue(1, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (5,), 2)
        nat_blobs = [c.drain_requests() for c in nat]
        py_blobs = [c.drain_requests() for c in py]
        assert nat_blobs == py_blobs
        for b in nat_blobs:
            nat[0].ingest(b)
        for b in py_blobs:
            py[0].ingest(b)
        nat_resp = nat[0].compute_responses()
        py_resp = py[0].compute_responses()
        assert nat_resp == py_resp
        rl = wire.parse_response_list(py_resp)
        assert len(rl.responses) == 2
        assert sorted(rs.process_set_id for rs in rl.responses) == [1, 2]

    def test_shutdown_semantics_bytes_identical(self):
        """Coordinated shutdown: global quiesce flag only when EVERY
        rank announced; pending tensors requiring an announced rank
        fail promptly with identical error bytes in both impls."""
        nat = make_pair(ncore.NativeController, size=2, fusion=1 << 10)
        py = make_pair(fallback.PyController, size=2, fusion=1 << 10)
        for pair in (nat, py):
            pair[0].enqueue(1, "stranded", wire.ALLREDUCE, wire.RED_SUM,
                            6, (4,))
            pair[1].set_shutdown()
        nat_blobs = [c.drain_requests() for c in nat]
        py_blobs = [c.drain_requests() for c in py]
        assert nat_blobs == py_blobs
        for b in nat_blobs:
            nat[0].ingest(b)
        for b in py_blobs:
            py[0].ingest(b)
        nat_resp = nat[0].compute_responses()
        py_resp = py[0].compute_responses()
        assert nat_resp == py_resp
        rl = wire.parse_response_list(py_resp)
        assert not rl.shutdown  # only rank 1 announced
        assert len(rl.responses) == 1
        assert rl.responses[0].error == "rank 1 has shut down"
        # rank 0 announces too -> global quiesce
        for pair in (nat, py):
            pair[0].set_shutdown()
        nat_blobs = [c.drain_requests() for c in nat]
        py_blobs = [c.drain_requests() for c in py]
        for b in nat_blobs:
            nat[0].ingest(b)
        for b in py_blobs:
            py[0].ingest(b)
        nat_resp = nat[0].compute_responses()
        py_resp = py[0].compute_responses()
        assert nat_resp == py_resp
        assert wire.parse_response_list(py_resp).shutdown

    def test_bypass_and_resync_cycle_bytes_identical(self):
        """The v3 steady-state protocol — bypass bit-vector blobs, the
        periodic full-resync cadence, and the responses they produce —
        must agree byte-for-byte between the C++ and Python
        controllers across enough cycles to cover every phase."""
        nat = make_pair(ncore.NativeController, size=2, fusion=1 << 10,
                        resync_every=3)
        py = make_pair(fallback.PyController, size=2, fusion=1 << 10,
                       resync_every=3)
        saw_bypass = saw_resync = False
        for step in range(8):
            for pair in (nat, py):
                for c in pair:
                    c.enqueue(step * 10 + c.rank + 1, "w/kernel",
                              wire.ALLREDUCE, wire.RED_AVERAGE, 6,
                              (64, 64))
                    c.enqueue(step * 10 + c.rank + 5, "w/bias",
                              wire.ALLREDUCE, wire.RED_AVERAGE, 6,
                              (64,))
            nat_blobs = [c.drain_requests() for c in nat]
            py_blobs = [c.drain_requests() for c in py]
            assert nat_blobs == py_blobs, f"step {step}"
            parsed = wire.parse_request_list(py_blobs[0])
            saw_bypass |= parsed.cache_bypass
            saw_resync |= parsed.cache_resync
            for b in nat_blobs:
                nat[0].ingest(b)
            for b in py_blobs:
                py[0].ingest(b)
            nat_resp = nat[0].compute_responses()
            py_resp = py[0].compute_responses()
            assert nat_resp == py_resp, f"step {step}"
            nat_fins = [c.apply_responses(nat_resp) for c in nat]
            py_fins = [c.apply_responses(py_resp) for c in py]
            assert nat_fins == py_fins
        assert saw_bypass and saw_resync

    def test_resync_needed_recovery_bytes_identical(self):
        """The unknown-bit -> cache_resync_needed -> in-flight
        re-announcement path produces identical bytes in both impls."""
        rogue = wire.serialize_request_list(wire.RequestList(
            rank=1, cache_bypass=True,
            cache_bits=wire.bits_to_words([9])))
        force = wire.serialize_response_list(wire.ResponseList(
            cache_resync_needed=True))
        outs = []
        for cls in (ncore.NativeController, fallback.PyController):
            c0, c1 = make_pair(cls, size=2)
            c1.enqueue(4, "x", wire.ALLREDUCE, wire.RED_SUM, 6, (2, 3))
            c1.drain_requests()          # x now in flight at rank 1
            c0.ingest(rogue)
            resp = c0.compute_responses()
            c1.apply_responses(force)
            reann = c1.drain_requests()
            outs.append((resp, reann))
        assert outs[0] == outs[1]
        assert wire.parse_response_list(outs[0][0]).cache_resync_needed
        parsed = wire.parse_request_list(outs[0][1])
        assert parsed.cache_resync
        assert [rq.entry.name for rq in parsed.requests] == ["x"]

    def test_mismatch_diagnostics_bytes_identical(self):
        """Cross-rank mismatch error responses (named-rank diagnostics
        + forced cache resync) must serialize identically from the C++
        and Python controllers — including after a bypass cycle where
        one rank's bit expands against another rank's conflicting full
        entry."""
        outs = []
        for cls in (ncore.NativeController, fallback.PyController):
            c0, c1 = make_pair(cls, size=2)
            c0.enqueue(1, "w/k", wire.ALLREDUCE, wire.RED_SUM, 6, (4, 4))
            c1.enqueue(1, "w/k", wire.ALLREDUCE, wire.RED_SUM, 6, (4, 8))
            resp, _fin = run_cycle([c0, c1])
            # dtype mismatch on a broadcast with disagreeing roots too
            c0.enqueue(2, "b", wire.BROADCAST, wire.RED_SUM, 6, (2,),
                       0, -1, 0)
            c1.enqueue(2, "b", wire.BROADCAST, wire.RED_SUM, 3, (2,),
                       0, -1, 1)
            resp2, _fin = run_cycle([c0, c1])
            outs.append((resp, resp2))
        assert outs[0] == outs[1]
        rl = wire.parse_response_list(outs[0][0])
        assert rl.cache_resync_needed
        assert len(rl.responses) == 1
        err = rl.responses[0].error
        assert err.startswith("cross-rank tensor mismatch for 'w/k'")
        assert "rank 0 submitted op=0 red_op=0 dtype=6 shape=[4,4]" in err
        assert "rank 1 submitted op=0 red_op=0 dtype=6 shape=[4,8]" in err
        rl2 = wire.parse_response_list(outs[0][1])
        err2 = rl2.responses[0].error
        assert "dtype=6" in err2 and "dtype=3" in err2
        assert "root_rank=0" in err2 and "root_rank=1" in err2

    def test_cross_impl_fleet(self):
        """Rank 0 native + rank 1 Python coordinate successfully."""
        c0 = ncore.NativeController(0, 2, 1 << 20)
        c1 = fallback.PyController(1, 2, 1 << 20)
        for step in range(2):
            for c in (c0, c1):
                c.enqueue(step + 1, "mixed", wire.ALLREDUCE,
                          wire.RED_SUM, 6, (16,))
            resp, fin = run_cycle([c0, c1])
            assert fin == [[step + 1], [step + 1]]


@pytest.mark.skipif(not NATIVE, reason="no C++ toolchain")
class TestNativeUtilities:
    def test_parallel_gather_scatter(self):
        import numpy as np

        srcs = [np.arange(i * 7, i * 7 + 13, dtype=np.uint8) for i in range(5)]
        total = sum(s.nbytes for s in srcs)
        dst = bytearray(total)
        ncore.parallel_gather(memoryview(dst),
                              [memoryview(s) for s in srcs])
        assert bytes(dst) == b"".join(s.tobytes() for s in srcs)
        outs = [bytearray(13) for _ in range(5)]
        ncore.parallel_scatter(memoryview(bytes(dst)),
                               [memoryview(o) for o in outs])
        for s, o in zip(srcs, outs):
            assert bytes(o) == s.tobytes()

    def test_timeline_writer(self, tmp_path):
        p = tmp_path / "tl.json"
        tl = ncore.NativeTimeline(str(p), rank=0)
        tl.event("NEGOTIATE_ALLREDUCE", "B", "negotiate", 1.0)
        tl.event("NEGOTIATE_ALLREDUCE", "E", "negotiate", 2.0)
        tl.event("XLA_COLLECTIVE", "X", "comm", 3.0, 4.5)
        tl.mark_cycle(10.0)
        tl.close()
        events = json.loads(p.read_text())
        assert [e["ph"] for e in events] == ["B", "E", "X", "i"]
        assert events[2]["dur"] == 4.5

    def test_pool_threads(self):
        lib = ncore.load()
        assert lib.hvt_pool_num_threads() >= 1


def test_make_controller_fallback_env(monkeypatch):
    monkeypatch.setenv("HVTPU_FORCE_PY_CONTROLLER", "1")
    c = native.make_controller(0, 1, 1 << 20)
    assert isinstance(c, fallback.PyController)


class TestNativeGaussianProcess:
    """native/src/gaussian_process.cc vs the numpy executable-spec twin
    (obs/gaussian_process.py) — parity: gaussian_process.cc +
    bayesian_optimization.cc keeping the GP math native."""

    def _data(self, n=15, d=2, seed=3):
        rng = np.random.RandomState(seed)
        xs = rng.rand(n, d)
        ys = np.sin(3 * xs[:, 0]) * np.cos(2 * xs[:, 1]) + 0.05 * rng.randn(n)
        cand = rng.rand(64, d)
        return xs, ys, cand

    def test_predict_matches_numpy_twin(self, monkeypatch):
        if not ncore.available():
            pytest.skip("no native toolchain")
        from horovod_tpu.obs import gaussian_process as gpmod

        xs, ys, cand = self._data()
        out = ncore.gp_predict(xs, ys, cand, length_scale=0.3,
                               noise=1e-4, signal_variance=1.0)
        assert out is not None
        mu_n, sig_n = out
        gp = gpmod.GaussianProcess(length_scale=0.3, noise=1e-4)
        gp.fit(xs, ys)
        monkeypatch.setenv("HVTPU_FORCE_PY_GP", "1")  # numpy twin
        mu_p, sig_p = gp.predict(cand)
        np.testing.assert_allclose(mu_n, mu_p, atol=1e-10)
        np.testing.assert_allclose(sig_n, sig_p, atol=1e-10)

    def test_ei_matches_numpy_twin(self, monkeypatch):
        if not ncore.available():
            pytest.skip("no native toolchain")
        from horovod_tpu.obs import gaussian_process as gpmod

        xs, ys, cand = self._data(seed=7)
        ei_n = ncore.gp_expected_improvement(
            xs, ys, cand, length_scale=0.3, noise=1e-4,
            signal_variance=1.0, best_y=float(ys.max()), xi=0.01,
        )
        assert ei_n is not None
        gp = gpmod.GaussianProcess(length_scale=0.3, noise=1e-4)
        gp.fit(xs, ys)
        monkeypatch.setenv("HVTPU_FORCE_PY_GP", "1")
        ei_p = gpmod.expected_improvement(gp, cand, float(ys.max()))
        np.testing.assert_allclose(ei_n, ei_p, atol=1e-10)

    def test_gp_predict_routes_native_by_default(self, monkeypatch):
        if not ncore.available():
            pytest.skip("no native toolchain")
        monkeypatch.delenv("HVTPU_FORCE_PY_GP", raising=False)
        from horovod_tpu.obs import gaussian_process as gpmod

        xs, ys, cand = self._data(seed=9)
        gp = gpmod.GaussianProcess(length_scale=0.3, noise=1e-4)
        gp.fit(xs, ys)
        mu_native, _ = gp.predict(cand)        # native route
        monkeypatch.setenv("HVTPU_FORCE_PY_GP", "1")
        mu_numpy, _ = gp.predict(cand)         # twin route
        np.testing.assert_allclose(mu_native, mu_numpy, atol=1e-10)

    def test_singular_gram_falls_back(self):
        if not ncore.available():
            pytest.skip("no native toolchain")
        # duplicate points with zero noise -> non-PD Gram; native
        # returns None and the numpy twin (with jitter) still answers
        xs = np.zeros((4, 2))
        ys = np.ones(4)
        out = ncore.gp_predict(xs, ys, np.zeros((1, 2)),
                               length_scale=0.3, noise=0.0,
                               signal_variance=1.0)
        assert out is None

    def test_shape_mismatch_raises(self):
        if not ncore.available():
            pytest.skip("no native toolchain")
        xs, ys, _ = self._data()
        with pytest.raises(ValueError, match="shape mismatch"):
            ncore.gp_predict(xs, ys, np.zeros((4, xs.shape[1] + 1)),
                             length_scale=0.3, noise=1e-4,
                             signal_variance=1.0)
        with pytest.raises(ValueError, match="shape mismatch"):
            ncore.gp_expected_improvement(
                xs, ys[:-1], np.zeros((4, xs.shape[1])),
                length_scale=0.3, noise=1e-4, signal_variance=1.0,
                best_y=0.0, xi=0.01,
            )


class TestWheelBuild:
    """pip install compiles the native core into the wheel (parity:
    the reference's setup.py/CMake build — SURVEY.md §2.3; VERDICT
    round-2 task 8: 'pip install . on a clean box yields the C++
    path')."""

    def test_wheel_contains_loadable_native_core(self, tmp_path):
        import ctypes
        import glob
        import subprocess
        import sys
        import zipfile

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        wheel_dir = tmp_path / "whl"
        r = subprocess.run(
            [sys.executable, "-m", "pip", "wheel", "--no-deps",
             "--no-build-isolation", "-w", str(wheel_dir), repo],
            capture_output=True, text=True, timeout=600,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        (whl,) = glob.glob(str(wheel_dir / "*.whl"))
        names = zipfile.ZipFile(whl).namelist()
        assert "horovod_tpu/native/libhvt_core.so" in names

        # the wheel's artifact loads standalone and speaks the exact
        # ABI core.py expects — i.e. an installed user gets the C++
        # control plane, not the Python twin
        site = tmp_path / "site"
        zipfile.ZipFile(whl).extractall(site)
        lib = ctypes.CDLL(str(site / "horovod_tpu/native/libhvt_core.so"))
        lib.hvt_abi_version.restype = ctypes.c_int
        assert lib.hvt_abi_version() == 5
