"""Scaling harness: measurement + the defended 256-chip projection.

The reference's headline claim is '~90% scaling efficiency at 128
GPUs' (README.rst Benchmarks / docs/benchmarks.rst); this repo's north
star is >=90% linear on a v5e-256 pod (BASELINE.md).  One chip cannot
measure that, so this harness defends the claim three ways:

  --mode sweep          throughput vs device count on a virtual CPU
                        mesh (mechanics only: virtual devices share one
                        core pool, so efficiency ~ 1/N by construction;
                        on a pod the same code measures real ICI).
  --mode coordination   MEASURED per-op coordination cost vs P over
                        real worker processes: sync eager collectives
                        (including the stall-watchdog rendezvous) and
                        the async controller cycle.  These costs bound
                        the *eager* path; the jitted DP step has no
                        per-step coordination at all (XLA's schedule is
                        static), which is the structural argument.
  --mode project        the analytic v5e-256 projection: measured
                        single-chip step times (BENCH_MODELS/BENCH_r03)
                        + gradient bytes vs ICI ring bandwidth with an
                        overlap budget, every assumption stated in the
                        output.
  --mode all            run coordination + project (+ sweep unless
                        --skip-sweep) and write BENCH_SCALING.json.

Methodology matches docs/benchmarks.rst (synthetic data, images/sec at
N over N x images/sec at 1); the projection model is the standard ring
allreduce cost 2*S*(N-1)/N bytes/chip (scaling-book recipe) against
the round-3 profiled step.
"""

import argparse
import json
import time

# ---------------------------------------------------------------------------
# measured single-chip inputs (BENCH_r03.json / BENCH_MODELS.json) and
# public hardware constants — every number the projection uses, in one
# visible table.
# ---------------------------------------------------------------------------

MEASURED = {
    # model: (params_millions, batch_per_chip, img_per_sec single chip)
    "resnet50": (25.56, 256, 2631.9),
    "resnet101": (44.55, 128, 1871.5),
    "inception3": (23.83, 128, 2132.8),
    "vgg16": (138.36, 64, 1076.4),
}

ASSUMPTIONS = {
    "wire_bytes_per_param": 2,          # bf16 gradient wire
    "ici_per_chip_gbps": 1600,          # v5e public spec: 1,600 Gbps ICI/chip
    "ici_allreduce_usable_fraction": 0.5,   # one direction of the torus
    #   links carries the ring's payload flow; 0.5 of aggregate is the
    #   conservative usable share (2D-torus multi-ring recovers more)
    "ici_derate_case": 0.125,           # pessimistic case: 4x worse than
    #   the usable-fraction estimate (200 GB/s -> 25 GB/s)
    "overlap_budget_fraction": 0.5,     # allreduce overlaps backprop;
    #   half the step is a conservative overlappable window (the
    #   reference's pipelined fusion cycle achieves its 90% with this)
    "v5e_slice_note": "v5e-256 (16x16 torus) is ONE ICI domain; DCN "
                      "enters only across slices (>256 chips), where "
                      "hierarchical allreduce reduces the cross-slice "
                      "payload to S/256 per chip — negligible.",
}


def project(ns=(8, 32, 256)):
    """Predicted DP scaling efficiency on v5e from the ring-allreduce
    cost model: eff(N) = t_step / (t_step + max(0, t_ar - overlap))."""
    a = ASSUMPTIONS
    bw_base = a["ici_per_chip_gbps"] / 8 * a["ici_allreduce_usable_fraction"]
    bw_worst = a["ici_per_chip_gbps"] / 8 * a["ici_derate_case"]
    out = []
    for model, (mparams, batch, ips) in MEASURED.items():
        t_step = batch / ips  # seconds
        s_bytes = mparams * 1e6 * a["wire_bytes_per_param"]
        overlap = a["overlap_budget_fraction"] * t_step
        for label, bw in (("base", bw_base), ("derate4x", bw_worst)):
            for n in ns:
                t_ar = 2 * s_bytes * (n - 1) / n / (bw * 1e9)
                exposed = max(0.0, t_ar - overlap)
                eff = t_step / (t_step + exposed)
                eff_noov = t_step / (t_step + t_ar)
                out.append({
                    "model": model, "chips": n, "bw_case": label,
                    "bw_GBps_per_chip": round(bw, 1),
                    "t_step_ms": round(t_step * 1e3, 2),
                    "t_allreduce_ms": round(t_ar * 1e3, 2),
                    "predicted_efficiency": round(eff, 4),
                    "predicted_efficiency_no_overlap": round(eff_noov, 4),
                })
    return out


# ---------------------------------------------------------------------------
# coordination cost vs P (REAL processes over the launcher)
# ---------------------------------------------------------------------------

def _coordination_body(iters):
    """Per-rank measurement body (runs in a launcher worker)."""
    import numpy as np
    import jax.numpy as jnp

    import horovod_tpu as hvt

    hvt.init()

    def timed(fn, reps):
        fn()  # warm (compile + cache)
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps * 1e3  # ms

    small = jnp.ones((1024,), jnp.float32)          # 4 KB
    big = jnp.ones((1024 * 1024,), jnp.float32)     # 4 MB

    res = {
        "sync_allreduce_4KB_ms": timed(
            lambda: np.asarray(hvt.allreduce(small, op=hvt.Sum)), iters),
        "sync_allreduce_4MB_ms": timed(
            lambda: np.asarray(hvt.allreduce(big, op=hvt.Sum)), iters),
        "async_cycle_4KB_ms": timed(
            lambda: hvt.synchronize(hvt.allreduce_async(small, op=hvt.Sum)),
            iters),
    }
    hvt.shutdown()
    return res


def coordination(iters=30, ps=(1, 2, 4, 8)):
    """Mean per-op latency vs P: the coordination floor of the eager
    path (KV rendezvous + gloo collective + dispatch).  The jit path
    carries none of this — coordination there is compile-time."""
    from horovod_tpu.runner import run

    rows = []
    for p in ps:
        results = run(_coordination_body, args=(iters,), np=p,
                      cpu_devices=1, timeout=900.0)
        agg = {k: round(max(r[k] for r in results), 3)
               for k in results[0]}
        rows.append({"processes": p, **agg})
    # stall-watchdog cost isolated at P=4: the default rows above run
    # the amortized mode; compare against the round-4 strict per-op
    # rendezvous and against checking disabled (the amortized target:
    # within noise of disabled — VERDICT r4 #1)
    for label, env in (
            ("amortized", {}),
            ("strict", {"HVTPU_STALL_CHECK_MODE": "strict"}),
            ("disabled", {"HVTPU_STALL_CHECK_DISABLE": "1"})):
        results = run(_coordination_body, args=(iters,), np=4,
                      cpu_devices=1, env=env or None, timeout=900.0)
        rows.append({
            "processes": 4, "stall_check": label,
            **{k: round(max(r[k] for r in results), 3)
               for k in results[0]},
        })
    return rows


# ---------------------------------------------------------------------------
# the virtual-mesh sweep (round-3 harness, unchanged mechanics)
# ---------------------------------------------------------------------------

def sweep(args):
    import jax

    if args.platform == "cpu":
        from horovod_tpu.core.state import force_cpu_devices

        force_cpu_devices(args.devices)

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    import horovod_tpu as hvt

    hvt.init()
    all_devs = jax.devices()

    if args.model == "mlp":
        from horovod_tpu.models.mlp import MLP

        model = MLP(features=(1024, 1024, 256), num_classes=100)
        x_shape = (784,)
    else:
        from horovod_tpu.models import ResNet18

        model = ResNet18(num_classes=100, dtype=jnp.bfloat16)
        x_shape = (64, 64, 3)

    rng = jax.random.PRNGKey(0)

    def throughput(devs):
        d = len(devs)
        mesh = Mesh(np.array(devs), ("dp",))
        gb = args.batch_per_device * d
        x = jax.random.normal(rng, (gb,) + x_shape,
                              jnp.bfloat16 if args.model == "resnet18"
                              else jnp.float32)
        y = jax.random.randint(rng, (gb,), 0, 100)
        variables = model.init(rng, x[:2]) if args.model == "mlp" else \
            model.init(rng, x[:2], train=True)

        tx = hvt.DistributedOptimizer(optax.sgd(0.1), axis_name="dp")
        params = variables["params"]
        extra = {k: v for k, v in variables.items() if k != "params"}
        opt_state = tx.init(params)

        def loss_fn(params, x, y):
            if extra:
                logits, _ = model.apply(
                    {"params": params, **extra}, x, train=True,
                    mutable=list(extra),
                )
            else:
                logits = model.apply({"params": params}, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        def body(params, opt_state, x, y):
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state,
                    jax.lax.pmean(loss, "dp"))

        step = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(), P("dp"), P("dp")),
                out_specs=(P(), P(), P()),
                check_vma=False,
            ),
            donate_argnums=(0, 1),
        )
        for _ in range(3):
            params, opt_state, loss = step(params, opt_state, x, y)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(args.iters):
            params, opt_state, loss = step(params, opt_state, x, y)
        float(loss)
        dt = time.perf_counter() - t0
        return gb * args.iters / dt

    results = []
    base = None
    d = 1
    while d <= len(all_devs):
        ips = throughput(all_devs[:d])
        if base is None:
            base = ips
        eff = ips / (base * d)
        results.append({
            "bench": "scaling", "model": args.model, "devices": d,
            "img_per_sec": round(ips, 1),
            "efficiency_vs_linear": round(eff, 4),
        })
        d *= 2
    return results


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mode", default="sweep",
                   choices=["sweep", "coordination", "project", "all"])
    p.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--devices", type=int, default=8,
                   help="virtual device count for --platform cpu")
    p.add_argument("--batch-per-device", type=int, default=64)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--model", default="mlp", choices=["mlp", "resnet18"])
    p.add_argument("--skip-sweep", action="store_true")
    p.add_argument("--out", default="BENCH_SCALING.json",
                   help="output file for --mode all")
    args = p.parse_args()

    if args.mode == "sweep":
        for r in sweep(args):
            print(json.dumps(r))
        return
    if args.mode == "coordination":
        for r in coordination(iters=args.iters):
            print(json.dumps(r))
        return
    if args.mode == "project":
        for r in project():
            print(json.dumps(r))
        return

    # --mode all: assemble BENCH_SCALING.json
    doc = {
        "bench": "scaling_sweep",
        "recorded": "round 4",
        "north_star": "≥90% linear DP scaling on v5e-256 (BASELINE.md)",
        "verdict": None,  # filled below
        "projection_assumptions": ASSUMPTIONS,
        "projection": project(),
        "coordination_vs_P": coordination(iters=args.iters),
    }
    if not args.skip_sweep:
        doc["virtual_mesh_sweep_note"] = (
            "Mechanics record only: virtual CPU devices share one "
            "physical core pool, so efficiency ~ 1/N by construction; "
            "on a pod the sweep runs unmodified for real ICI numbers.")
        doc["virtual_mesh_sweep"] = sweep(args)
    # verdict derives every number from the projection rows so it can
    # never contradict (or outlive) its own table
    def cell(model, case, key="predicted_efficiency_no_overlap"):
        return next(r[key] for r in doc["projection"]
                    if r["chips"] == 256 and r["model"] == model
                    and r["bw_case"] == case)

    r50_worst = cell("resnet50", "derate4x")
    r50_base = cell("resnet50", "base")
    r50_overlap = cell("resnet50", "derate4x", "predicted_efficiency")
    vgg_worst = cell("vgg16", "derate4x")
    s_mb = MEASURED["resnet50"][0] * ASSUMPTIONS["wire_bytes_per_param"]
    t_ms = next(r["t_step_ms"] for r in doc["projection"]
                if r["model"] == "resnet50")
    ar_ms = [r["t_allreduce_ms"] for r in doc["projection"]
             if r["chips"] == 256 and r["model"] == "resnet50"]
    doc["verdict"] = (
        f"ResNet-50 predicted efficiency at 256 chips: {r50_worst:.3f} "
        "with ZERO overlap credit AND a 4x ICI bandwidth derate (the "
        f"worst modeled case; {r50_base:.3f} at base bandwidth, "
        f"{r50_overlap:.2f} with the stated overlap budget) — the ≥90% "
        "north star holds with margin because the jitted DP step "
        "carries no per-step coordination and the bf16 gradient "
        f"allreduce ({s_mb:.0f} MB/chip) is "
        f"{min(ar_ms):.1f}-{max(ar_ms):.1f} ms against a {t_ms:.0f} ms "
        f"step. The comm-bound outlier is VGG-16 ({vgg_worst:.2f} in "
        "the same worst case), matching the reference's own ~68% claim "
        "shape. See docs/benchmarks.md §Scaling.")
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({"wrote": args.out, "verdict": doc["verdict"]}))


if __name__ == "__main__":
    main()
