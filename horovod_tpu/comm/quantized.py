"""Quantized (int8-wire) allreduce — EQuARX-style (PAPERS.md:
"EQuARX: Efficient Quantized AllReduce in XLA", arXiv:2506.17615).

A plain ``psum`` cannot carry block-quantized int8: summing codes
quantized against different per-rank scales is meaningless and int8
accumulation overflows.  EQuARX therefore quantizes *per hop* inside
the collective.  At the JAX level we express the same structure as the
two-phase allreduce XLA itself uses:

  1. **reduce-scatter phase** — ``all_to_all`` the int8-quantized
     shards (each rank's chunk c quantized with that rank's scale,
     scales ride alongside as fp32 per-block sidecars), then each rank
     dequantizes the N received chunks and sums them in fp32 — wire
     bytes: 1 B/elt instead of 4 (plus 4/BLOCK scale overhead);
  2. **allgather phase** — the reduced chunk is re-quantized and
     ``all_gather``-ed, again 1 B/elt on the wire.

Total wire ≈ 2·(N-1)/N bytes/elt vs 8·(N-1)/N for fp32 psum — the same
~4× saving as EQuARX, with one quantization error per phase (two total),
matching the paper's error model.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

BLOCK = 512


def _quantize(x, *, key=None):
    """x: (..., k) fp32 → int8 codes + fp32 per-block scales.

    ``key`` (a jax PRNG key) switches to stochastic rounding:
    ``floor(x/s + u)``, u ~ U[0,1), which is unbiased (E[q·s] = x) so
    quantisation noise cancels instead of accumulating when the codes
    feed a summation across ranks."""
    n = x.shape[-1]
    pad = (-n) % BLOCK
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    blocks = x.reshape(x.shape[:-1] + (-1, BLOCK))
    scale = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True) / 127.0
    safe = jnp.where(scale == 0, 1.0, scale)
    scaled = blocks / safe
    if key is not None:
        u = jax.random.uniform(key, scaled.shape, jnp.float32)
        q = jnp.clip(jnp.floor(scaled + u), -127, 127).astype(jnp.int8)
    else:
        q = jnp.clip(jnp.round(scaled), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _dither_key(flat, axis_name):
    """Traced PRNG key for stochastic rounding: folds the rank index
    (decorrelates dither across ranks — the property cross-rank error
    cancellation needs) and a fold of the payload bits (varies the
    dither per step under jit, where a Python-level seed would bake
    into the compiled program as a constant)."""
    bits = lax.bitcast_convert_type(flat.astype(jnp.float32), jnp.int32)
    key = jax.random.fold_in(jax.random.key(0x51DE), lax.axis_index(axis_name))
    return jax.random.fold_in(key, jnp.sum(bits).astype(jnp.uint32))


def quantized_allreduce(tensor, *, axis_name: str, average: bool = False,
                        stochastic: bool = False):
    """int8-wire allreduce of a float tensor inside shard_map/jit.

    The tensor is flattened and padded so each participant owns an
    equal chunk.  Returns fp32 (caller casts back).

    ``stochastic=True`` rounds with a traced per-(rank, payload) PRNG
    key (see :func:`_dither_key`) so the per-rank quantisation errors
    are independent and cancel ~√N-style in the phase-1 summation
    instead of adding coherently — the error model EQuARX assumes.

    ``HVTPU_QUANTIZED_RING=1`` routes through the Pallas per-hop
    requantizing ring kernel instead (ops/ring.py — the EQuARX
    algorithm proper, requantizing on every hop rather than once per
    phase); only takes effect where the kernel can run (TPU, or the
    interpreter in tests).  The ring kernel rounds deterministically,
    so ``stochastic=True`` keeps the XLA path — the documented
    unbiased-dither semantics win over the ring opt-in.
    """
    import os

    n_ranks = lax.axis_size(axis_name)
    if (os.environ.get("HVTPU_QUANTIZED_RING", "0") == "1"
            and n_ranks > 1 and not stochastic):
        try:
            # soft import: ring.py needs pallas importable; fall
            # through to the XLA path anywhere it isn't
            from ..ops.ring import _interpret_arg, ring_allreduce
        except Exception:
            ring_allreduce = None
        if ring_allreduce is not None and _interpret_arg() is not None:
            return ring_allreduce(
                tensor, axis_name=axis_name, average=average,
                quantized=True,
            )
    orig_shape = tensor.shape
    orig_dtype = tensor.dtype
    flat = tensor.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    chunk = -(-n // n_ranks)  # ceil
    pad = chunk * n_ranks - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunks = flat.reshape(n_ranks, chunk)

    key = _dither_key(flat, axis_name) if stochastic else None

    # Phase 1: reduce-scatter with int8 wire.  Stochastic rounding
    # matters HERE: the N dequantized contributions are summed, so
    # independent per-rank dither cancels while deterministic rounding
    # bias adds coherently.
    q, scale = _quantize(chunks, key=key)      # (N, chunk/B, B) int8 + scales
    q_recv = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    s_recv = lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    # q_recv: (N, chunk/B, B) — contribution of every rank to MY chunk.
    deq = q_recv.astype(jnp.float32) * s_recv
    reduced = jnp.sum(deq, axis=0)             # (chunk/B, B) fp32

    # Phase 2: allgather with int8 wire.  (Stochastic rounding here
    # keeps the result unbiased over steps; the error is common to all
    # ranks either way since each chunk is quantized once by its owner.)
    scale2 = jnp.max(jnp.abs(reduced), axis=-1, keepdims=True) / 127.0
    safe2 = jnp.where(scale2 == 0, 1.0, scale2)
    scaled2 = reduced / safe2
    if key is not None:
        u2 = jax.random.uniform(
            jax.random.fold_in(key, 1), scaled2.shape, jnp.float32
        )
        q2 = jnp.clip(jnp.floor(scaled2 + u2), -127, 127).astype(jnp.int8)
    else:
        q2 = jnp.clip(jnp.round(scaled2), -127, 127).astype(jnp.int8)
    q_all = lax.all_gather(q2, axis_name)      # (N, chunk/B, B)
    s_all = lax.all_gather(scale2.astype(jnp.float32), axis_name)
    deq_all = (q_all.astype(jnp.float32) * s_all).reshape(n_ranks, -1)
    # trim per-chunk block padding before concatenating ranks' chunks
    out = deq_all[:, :chunk].reshape(-1)[:n]

    if average:
        out = out / n_ranks
    return out.reshape(orig_shape).astype(
        orig_dtype if jnp.issubdtype(orig_dtype, jnp.floating) else jnp.float32
    )
