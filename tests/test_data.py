"""hvtpu.data: elastic-aware sharded input pipeline (ISSUE PR 9).

Units: permutation determinism, remainder re-sharding across a resize,
uneven-tail agreement, prefetch shutdown hygiene, exactly-once delivery
with rollback/restore, the elastic participant protocol, the
``data.next`` fault site, and the loader's observability surface.

Acceptance (slow/multiprocess): a 2-proc elastic run preempted
mid-epoch delivers every sample index exactly once across incarnations
(resuming from the drain-committed cursor), and ``hvtputrace report``
attributes an injected ``data.next:delay`` to the input phase of the
right rank.
"""

import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import horovod_tpu
from horovod_tpu import data as hvt_data
from horovod_tpu.core import faults
from horovod_tpu.data import (ArraySource, ElasticDataLoader,
                              FileListSource, LoaderState, Sharder,
                              SyntheticSource, sharder)

_REPO = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_SCRIPT = os.path.join(os.path.dirname(__file__),
                       "elastic_data_script.py")


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.uninstall()


def _make_loader(n=24, batch=4, **kw):
    kw.setdefault("device_put", False)
    src = ArraySource({"y": np.arange(n)})
    return ElasticDataLoader(src, batch_size=batch, **kw)


# ---------------------------------------------------------------------------
# sharder units
# ---------------------------------------------------------------------------

class TestSharder:
    def test_permutation_deterministic_per_seed_and_epoch(self):
        a = sharder.epoch_permutation(100, seed=5, epoch=3)
        b = sharder.epoch_permutation(100, seed=5, epoch=3)
        assert np.array_equal(a, b)
        assert sorted(a.tolist()) == list(range(100))
        # different epoch (or seed) -> different order, same sample set
        c = sharder.epoch_permutation(100, seed=5, epoch=4)
        d = sharder.epoch_permutation(100, seed=6, epoch=3)
        assert not np.array_equal(a, c)
        assert not np.array_equal(a, d)
        assert sorted(c.tolist()) == list(range(100))

    def test_no_shuffle_is_identity(self):
        p = sharder.epoch_permutation(7, seed=9, epoch=2, shuffle=False)
        assert p.tolist() == list(range(7))

    def test_world_consumes_disjoint_covering_shards(self):
        """One step: the per-rank pieces partition the window."""
        sh = Sharder(40, batch_size=4, seed=1)
        pieces = [sh.next_indices(epoch=0, cursor=0, rank=r, size=3)[0]
                  for r in range(3)]
        cursors = {sh.next_indices(0, 0, r, 3)[1] for r in range(3)}
        assert cursors == {12}  # all ranks agree on the new cursor
        flat = np.concatenate(pieces)
        assert len(flat) == 12 and len(set(flat.tolist())) == 12

    def test_resize_resharding_exactly_once(self):
        """Consume part of an epoch at size 3, finish it at size 2:
        the unconsumed remainder is re-split with nothing repeated or
        dropped — the tentpole's resize contract, in pure math."""
        n, batch = 40, 4
        sh = Sharder(n, batch, seed=11)
        delivered, cursor = [], 0
        for _ in range(2):  # two steps at size 3 (24 samples)
            for r in range(3):
                piece, nxt = sh.next_indices(0, cursor, r, 3)
                delivered.extend(piece.tolist())
            cursor = nxt
        assert cursor == 24
        # "relaunch" with size 2: a fresh Sharder (new incarnation)
        sh2 = Sharder(n, batch, seed=11)
        while cursor < n:
            for r in range(2):
                piece, nxt = sh2.next_indices(0, cursor, r, 2)
                delivered.extend(piece.tolist())
            cursor = nxt
        assert sorted(delivered) == list(range(n))

    def test_uneven_tail_agreement(self):
        """n=10, B=4, size=3: every rank computes the same step count;
        tail pieces differ by <= 1 and may be empty, and the world
        still covers every sample exactly once."""
        n, batch, size = 10, 4, 3
        assert all(
            sharder.steps_remaining(n, 0, size, batch) == 1
            for _ in range(size))
        sh = Sharder(n, batch, seed=2)
        pieces = [sh.next_indices(0, 0, r, size)[0] for r in range(size)]
        sizes = sorted(len(p) for p in pieces)
        assert max(sizes) - min(sizes) <= 1
        flat = np.concatenate(pieces)
        assert sorted(flat.tolist()) == sorted(
            sh.permutation(0)[:n].tolist())
        # a tail shorter than the world leaves trailing ranks empty
        sh5 = Sharder(2, 4, seed=2)
        tail = [sh5.next_indices(0, 0, r, 5)[0] for r in range(5)]
        assert [len(p) for p in tail].count(0) == 3

    def test_steps_remaining_is_rank_independent_mid_epoch(self):
        for cursor in (0, 7, 12, 39, 40):
            vals = {sharder.steps_remaining(40, cursor, 4, 4)}
            assert len(vals) == 1


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

class TestSources:
    def test_array_source_structure_gather(self):
        src = ArraySource({"x": np.arange(12).reshape(6, 2),
                           "y": np.arange(6)})
        b = src.fetch(np.array([4, 1]))
        assert b["x"].tolist() == [[8, 9], [2, 3]]
        assert b["y"].tolist() == [4, 1]

    def test_array_source_rejects_ragged(self):
        with pytest.raises(ValueError, match="disagree"):
            ArraySource({"x": np.zeros(4), "y": np.zeros(5)})

    def test_file_list_source(self, tmp_path):
        paths = []
        for i in range(4):
            p = tmp_path / f"s{i}.npy"
            np.save(p, np.full((3,), i))
            paths.append(str(p))
        src = FileListSource(paths, labels=[10, 11, 12, 13])
        x, y = src.fetch(np.array([2, 0]))
        assert x.shape == (2, 3) and x[0, 0] == 2
        assert y.tolist() == [12, 10]

    def test_synthetic_source_deterministic(self):
        a = SyntheticSource(100, (4, 4), seed=3)
        b = SyntheticSource(100, (4, 4), seed=3)
        ba, bb = a.fetch(np.arange(5)), b.fetch(np.arange(5))
        assert np.array_equal(ba["x"], bb["x"])
        assert np.array_equal(ba["y"], bb["y"])
        assert ba["x"].shape == (5, 4, 4)


# ---------------------------------------------------------------------------
# loader units
# ---------------------------------------------------------------------------

class TestLoader:
    def test_epoch_delivers_each_sample_once(self):
        ld = _make_loader(n=24, batch=4, seed=3)
        try:
            seen = [int(v) for b in ld for v in b["y"]]
            assert sorted(seen) == list(range(24))
            assert ld.state.epoch == 1 and ld.state.cursor == 0
        finally:
            ld.close()

    def test_prefetch_shutdown_leaves_no_thread(self):
        ld = _make_loader(n=24, batch=4)
        it = iter(ld)
        next(it)
        names = [t.name for t in threading.enumerate()]
        assert any("hvtpu-data-prefetch" in s for s in names)
        ld.close()
        deadline = time.time() + 5
        while time.time() < deadline:
            names = [t.name for t in threading.enumerate()]
            if not any("hvtpu-data-prefetch" in s for s in names):
                break
            time.sleep(0.05)
        assert not any("hvtpu-data-prefetch" in s for s in names)

    def test_restore_replays_only_uncommitted_batches(self):
        """Rollback semantics: restore() rewinds to the last commit;
        exactly the uncommitted batches are re-delivered (prefetched-
        but-undelivered data never counts as consumed)."""
        from horovod_tpu import elastic

        ld = _make_loader(n=24, batch=4, seed=5)
        try:
            st = elastic.ObjectState(data=ld.state, step=0)
            it = iter(ld)
            committed = [next(it)["y"].tolist(), next(it)["y"].tolist()]
            st.save_to_memory()
            lost = [next(it)["y"].tolist(), next(it)["y"].tolist()]
            st.restore()
            assert ld.state is st.data, "in-place restore lost identity"
            assert ld.state.cursor == 8
            replay = [b["y"].tolist() for b in ld]
            assert replay[0] == lost[0] and replay[1] == lost[1]
            everything = [v for b in committed + replay for v in b]
            assert sorted(everything) == list(range(24))
        finally:
            ld.close()

    def test_loader_state_rides_disk_commit(self, tmp_path, monkeypatch):
        """The participant protocol must survive the durable pickle
        path: commit in one 'incarnation', load in a fresh one."""
        from horovod_tpu import elastic

        monkeypatch.setenv("HVTPU_ELASTIC_STATE_DIR", str(tmp_path))
        ld = _make_loader(n=24, batch=4, seed=9)
        st = elastic.ObjectState(data=ld.state)
        it = iter(ld)
        first = [next(it)["y"].tolist() for _ in range(3)]
        st.save()
        st.wait_durable()
        ld.close()

        ld2 = _make_loader(n=24, batch=4, seed=9)
        st2 = elastic.ObjectState(data=ld2.state)
        try:
            import pickle

            from horovod_tpu.core import durable as core_durable

            seq = core_durable.latest_verified(str(tmp_path))
            assert seq is not None
            payload = core_durable.read_snapshot(
                str(tmp_path), seq)["state.pkl"]
            st2._from_disk_payload(pickle.loads(payload))
            assert ld2.state.cursor == 12 and ld2.state.seed == 9
            rest = [v for b in ld2 for v in b["y"].tolist()]
            flat = [v for b in first for v in b] + rest
            assert sorted(flat) == list(range(24))
        finally:
            ld2.close()

    def test_stream_crosses_epochs(self):
        ld = _make_loader(n=8, batch=4, seed=1)
        try:
            s = ld.stream()
            got = [next(s)["y"] for _ in range(5)]
            assert ld.state.epoch == 2
            assert all(len(g) == 4 for g in got)
        finally:
            ld.close()

    def test_with_indices_and_transform(self):
        calls = []
        ld = _make_loader(n=8, batch=4, with_indices=True,
                          transform=lambda b: calls.append(1) or b)
        try:
            idx, batch = next(iter(ld))
            assert np.array_equal(np.sort(idx), np.sort(batch["y"]))
            assert calls
        finally:
            ld.close()

    def test_debug_state_registered(self):
        from horovod_tpu.obs import metrics as obs_metrics

        ld = _make_loader(n=8, batch=4, name="dbg")
        try:
            next(iter(ld))
            snap = obs_metrics.debug_snapshot()
            assert "data" in snap
            entry = snap["data"]["dbg"]
            assert entry["samples"] == 8
            assert entry["delivered_batches"] >= 1
            assert entry["prefetch_alive"] is True
        finally:
            ld.close()

    def test_wait_metric_counts_batches(self):
        from horovod_tpu.obs import metrics as obs_metrics

        def wait_count():
            fam = obs_metrics.snapshot().get("hvtpu_data_wait_seconds")
            if not fam:
                return 0
            return sum(c["count"] for c in fam["values"].values())

        ld = _make_loader(n=8, batch=4)
        try:
            before = wait_count()
            list(ld)
            assert wait_count() - before == 2
        finally:
            ld.close()


# ---------------------------------------------------------------------------
# fault site
# ---------------------------------------------------------------------------

class TestDataFaultSite:
    def test_grammar_accepts_data_next(self):
        cs = faults.parse_spec(
            "data.next:delay(50)@rank=1;data.next:drop;data.next:error")
        assert [c.site for c in cs] == ["data.next"] * 3

    def test_delay_stalls_delivery(self):
        faults.install("data.next:delay(120)@times=1", rank=0)
        ld = _make_loader(n=8, batch=4)
        try:
            t0 = time.perf_counter()
            next(iter(ld))
            assert time.perf_counter() - t0 >= 0.12
        finally:
            ld.close()

    def test_drop_loses_one_batch_and_advances_cursor(self):
        faults.install("data.next:drop@times=1", rank=0)
        ld = _make_loader(n=12, batch=4, seed=4)
        try:
            seen = [v for b in ld for v in b["y"].tolist()]
            # one injected drop: 4 of 12 samples lost, none repeated
            assert len(seen) == 8 and len(set(seen)) == 8
            assert ld.state.epoch == 1 and ld.state.cursor == 0
        finally:
            ld.close()

    def test_error_raises_injected_fault(self):
        faults.install("data.next:error@times=1", rank=0)
        ld = _make_loader(n=8, batch=4)
        try:
            with pytest.raises(faults.InjectedFault):
                next(iter(ld))
        finally:
            ld.close()


# ---------------------------------------------------------------------------
# acceptance: 2-proc elastic exactly-once + trace attribution
# ---------------------------------------------------------------------------

_DELIVER_RE = re.compile(
    r"DELIVER rank=(\d+) size=(\d+) gen=(\d+) epoch=(\d+) "
    r"idx=\[([0-9, ]*)\]")


def _launch_data_elastic(tmp_path, fault_spec, epochs=2, samples=48,
                         batch=4, timeout=300):
    from conftest import make_discovery_script

    _hosts, disc = make_discovery_script(tmp_path, "localhost:2")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELASTIC_EPOCHS"] = str(epochs)
    env["DATA_SAMPLES"] = str(samples)
    env["DATA_BATCH"] = str(batch)
    env["EPOCH_SLEEP"] = "0.3"
    env["HVTPU_ELASTIC_DISCOVERY_INTERVAL"] = "0.2"
    cmd = [
        sys.executable, "-m", "horovod_tpu.runner",
        "--host-discovery-script", disc,
        "--min-np", "2", "--cpu-devices", "1", "--verbose",
        "--fault-spec", fault_spec,
        "--", sys.executable, _SCRIPT,
    ]
    res = subprocess.run(cmd, env=env, cwd=_REPO, timeout=timeout,
                         capture_output=True, text=True)
    return res, res.stdout + res.stderr


@pytest.mark.multiprocess
@pytest.mark.slow
def test_preempt_mid_epoch_delivers_each_sample_exactly_once(tmp_path):
    """ISSUE-9 acceptance: rank 1 is preempted at its 3rd per-batch
    commit (mid-epoch).  The drain commits the loader cursor, the
    driver resizes 2->2, and across both incarnations every sample
    index of every epoch is delivered exactly once — no repeats from
    restarting the epoch, no drops from the in-flight prefetch."""
    epochs, samples = 2, 48
    res, out = _launch_data_elastic(
        tmp_path, "worker.step:preempt@rank=1,count=3", epochs=epochs,
        samples=samples)
    assert res.returncode == 0, out[-4000:]
    assert "exiting 79 for a planned departure" in out, out[-4000:]
    assert out.count("launching 2 workers") == 2, out[-4000:]
    assert f"DONE size=2 epoch={epochs}" in out, out[-4000:]
    per_epoch = {e: [] for e in range(epochs)}
    gens = set()
    for m in _DELIVER_RE.finditer(out):
        gens.add(int(m.group(3)))
        idx = [int(v) for v in m.group(5).split(",") if v.strip()]
        per_epoch[int(m.group(4))].extend(idx)
    assert gens == {0, 1}, (gens, out[-4000:])
    for e in range(epochs):
        got = sorted(per_epoch[e])
        assert got == list(range(samples)), (
            f"epoch {e}: delivered {len(got)} samples "
            f"({len(set(got))} unique) — exactly-once violated")


@pytest.mark.multiprocess
@pytest.mark.slow
def test_trace_attributes_data_delay_to_input_phase(tmp_path):
    """ISSUE-9 acceptance: an injected ``data.next:delay`` on rank 1
    must show up in ``hvtputrace report`` as INPUT wait (data_wait) on
    rank 1 — not as compute, and bigger than rank 0's."""
    from horovod_tpu.runner import run as run_fn
    from tools import hvtputrace

    trace_dir = str(tmp_path / "traces")
    os.makedirs(trace_dir, exist_ok=True)

    def body():
        import numpy as _np

        import horovod_tpu as _hvt
        from horovod_tpu.data import ArraySource as _AS
        from horovod_tpu.data import ElasticDataLoader as _EDL

        _hvt.init()
        ld = _EDL(_AS({"y": _np.arange(32)}), batch_size=4,
                  device_put=False, name="traced")
        for _ in ld:
            pass
        ld.close()
        _hvt.shutdown()
        return "ok"

    env = {
        "PYTHONPATH": _REPO + os.pathsep + os.environ.get(
            "PYTHONPATH", ""),
        "HVTPU_TRACE": trace_dir,
        "HVTPU_FAULT_SPEC": "data.next:delay(80)@rank=1,times=3",
    }
    assert run_fn(body, np=2, cpu_devices=1, env=env,
                  start_timeout=300.0) == ["ok", "ok"]

    rep = hvtputrace.report(trace_dir)
    r0, r1 = rep["per_rank"][0], rep["per_rank"][1]
    # three 80 ms delays land inside rank 1's DATA_WAIT spans
    assert r1["data_wait_us"] > 200_000, rep["per_rank"]
    assert r1["data_wait_us"] > 4 * r0["data_wait_us"], rep["per_rank"]
    assert r1["data_wait_fraction"] > 0, rep["per_rank"]
    # the rendered report surfaces the input column
    text = hvtputrace.render_report(rep)
    assert "input_ms" in text and "input%" in text
