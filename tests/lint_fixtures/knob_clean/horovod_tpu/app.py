"""knob-registry fixture (clean): code and docs agree."""

import os

TIMEOUT = float(os.environ.get("HVTPU_FIXTURE_TIMEOUT", "30.0"))
