"""Shared flat-buffer pack/unpack used by every fused collective path
(the memcpy-in/out of the reference's fusion buffer,
horovod/common/ops/collective_operations.cc MemcpyInFusionBuffer /
MemcpyOutFusionBuffer — here expressed as XLA concat/slice that fuse
into the surrounding program)."""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax.numpy as jnp


def pack_flat(tensors: Sequence[Any]):
    """Concatenate tensors into one flat buffer in the promoted dtype.

    Returns (flat, specs) where specs = [(shape, dtype, size), ...] in
    input order.
    """
    tensors = [jnp.asarray(t) for t in tensors]
    if not tensors:
        raise ValueError("pack_flat requires at least one tensor")
    compute_dtype = jnp.result_type(*[t.dtype for t in tensors])
    flat = jnp.concatenate([t.reshape(-1).astype(compute_dtype) for t in tensors])
    specs = [(tuple(t.shape), t.dtype, t.size) for t in tensors]
    return flat, specs


def unpack_flat(flat, specs) -> List[Any]:
    """Inverse of pack_flat: slice, reshape, and cast back."""
    outs, off = [], 0
    for shape, dtype, size in specs:
        outs.append(flat[off : off + size].reshape(shape).astype(dtype))
        off += size
    return outs


def pack_bytes(raws, parallel: bool = True):
    """Pack host arrays into ONE uint8 buffer, byte-exact per dtype
    (the native-thread-pool fused path of broadcast_parameters /
    broadcast_variables — unlike :func:`pack_flat` there is no dtype
    promotion: each leaf's bytes ride verbatim).

    ``raws``: numpy arrays (any dtype incl. ml_dtypes bf16).  Returns
    ``(buf, specs)`` with specs = [(shape, dtype, nbytes), ...].
    NOTE: shapes are recorded BEFORE ``ascontiguousarray``, which
    promotes 0-d arrays to 1-d — the bug this helper exists to fix
    exactly once.
    """
    import numpy as np

    shapes = [r.shape for r in raws]
    vals = [np.ascontiguousarray(r) for r in raws]
    views = [v.reshape(-1).view(np.uint8) for v in vals]
    buf = np.empty(sum(v.nbytes for v in views), np.uint8)
    if parallel:
        from ..native import core as native_core

        native_core.parallel_gather(
            memoryview(buf), [memoryview(v) for v in views]
        )
    else:  # pragma: no cover - used only where native core is absent
        off = 0
        for v in views:
            buf[off:off + v.nbytes] = v
            off += v.nbytes
    specs = [(s, v.dtype, v.nbytes)
             for s, v in zip(shapes, vals)]
    return buf, specs


def unpack_bytes(buf, specs):
    """Inverse of :func:`pack_bytes` → list of numpy arrays (views
    where alignment allows, copies otherwise)."""
    import numpy as np

    out = []
    off = 0
    for shape, dtype, nbytes in specs:
        chunk = buf[off:off + nbytes]
        try:
            piece = chunk.view(dtype).reshape(shape)
        except ValueError:  # unaligned offset for this dtype
            piece = np.frombuffer(
                chunk.tobytes(), dtype=dtype
            ).reshape(shape)
        out.append(piece)
        off += nbytes
    return out
