"""Elastic tf.keras training example — the horovod_tpu analog of the
reference's examples/elastic/tensorflow2/tensorflow2_keras_mnist_elastic.py:
``hvd.elastic.run`` drives ``model.fit`` with ``KerasState`` and the
elastic state callbacks; commits survive worker loss and world
resizes, and a restarted/resized world resumes from the committed
epoch with weights re-broadcast from rank 0.

Run:
  hvtpurun --host-discovery-script ./discover.sh --min-np 2 \
      --cpu-devices 1 python examples/tensorflow2_keras_mnist_elastic.py
where discover.sh prints e.g. "localhost:4".
"""

import numpy as np

import horovod_tpu.tensorflow.keras as hvd


def main():
    import keras

    hvd.init()
    np.random.seed(0)
    x = np.random.rand(1024, 784).astype(np.float32)
    w = np.random.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(axis=1)

    model = keras.Sequential([
        keras.layers.Input((784,)),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])
    opt = hvd.DistributedOptimizer(
        keras.optimizers.SGD(0.05 * hvd.size()))
    model.compile(optimizer=opt,
                  loss="sparse_categorical_crossentropy")

    state = hvd.elastic.KerasState(model, optimizer=opt,
                                   batch=0, epoch=0)

    def on_reset():
        # world size changed: rescale the lr like the reference's
        # elastic keras example does on state reset
        opt.learning_rate = 0.05 * hvd.size()

    state.register_reset_callbacks([on_reset])
    epochs = 8

    @hvd.elastic.run
    def train(state):
        # shard by the CURRENT world — resizes survive
        n = len(x) // hvd.size()
        lo = hvd.rank() * n
        model.fit(
            x[lo:lo + n], y[lo:lo + n],
            batch_size=64,
            initial_epoch=state.epoch,
            epochs=epochs,
            verbose=1 if hvd.rank() == 0 else 0,
            callbacks=[
                hvd.callbacks.MetricAverageCallback(),
                hvd.elastic.UpdateBatchStateCallback(state),
                hvd.elastic.UpdateEpochStateCallback(state),
                hvd.elastic.CommitStateCallback(state),
            ],
        )

    train(state)
    if hvd.rank() == 0:
        print(f"done after epoch {state.epoch}; ranks consistent "
              f"({hvd.size()} ranks)", flush=True)


if __name__ == "__main__":
    main()
