"""Spark TorchEstimator example — the horovod_tpu port surface of the
reference's examples/spark/pytorch/pytorch_spark_mnist.py: build a
DataFrame, hand the model to the estimator, get a trained model back,
transform.  Frames are pandas here (a pyspark DataFrame works when
pyspark is installed); ranks are real worker processes launched by the
hvtpurun machinery.

Run:  python examples/spark_torch_estimator.py
"""

import argparse
import tempfile

import numpy as np
import pandas as pd
import torch
import torch.nn as nn
import torch.nn.functional as F

from horovod_tpu.spark import LocalStore, TorchEstimator


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-proc", type=int, default=2)
    p.add_argument("--train-size", type=int, default=2048)
    args = p.parse_args()

    # synthetic MNIST-shaped classification frame
    rng = np.random.RandomState(0)
    x = rng.rand(args.train_size, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(axis=1)
    df = pd.DataFrame({"features": list(x), "label": y})

    model = nn.Sequential(
        nn.Linear(784, 128), nn.ReLU(), nn.Linear(128, 10))

    with tempfile.TemporaryDirectory() as store_dir:
        est = TorchEstimator(
            model=model,
            optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
            loss=lambda logits, target: F.cross_entropy(
                logits, target.long()),
            feature_cols=["features"],
            label_cols=["label"],
            validation=0.1,
            batch_size=args.batch_size,
            epochs=args.epochs,
            num_proc=args.num_proc,
            store=LocalStore(store_dir),
            random_seed=42,
            verbose=0,
        )
        trained = est.fit(df)
        hist = trained.getHistory()
        print(f"loss history: {[round(v, 4) for v in hist['loss']]}")
        print(f"val_loss:     "
              f"{[round(v, 4) for v in hist['val_loss']]}")

        out = trained.transform(df)
        pred = np.stack(out["label__output"].to_numpy()).argmax(axis=1)
        acc = float((pred == y).mean())
        print(f"train accuracy after transform: {acc:.3f}")
        assert hist["loss"][-1] < hist["loss"][0]
        assert acc > 0.5
        print(f"estimator OK ({args.num_proc} ranks)")


if __name__ == "__main__":
    main()
