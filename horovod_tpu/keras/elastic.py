"""Elastic keras surface (parity: ``horovod/keras/elastic.py`` +
the shared ``horovod/_keras/elastic.py`` callback impls):
``KerasState`` plus the state-maintenance callbacks the reference's
elastic keras examples drive ``model.fit`` with, and the shared
``run`` decorator."""

import keras

from ..elastic import run  # noqa: F401  (parity: hvd.elastic.run)
from ..tensorflow.elastic import TensorFlowKerasState

KerasState = TensorFlowKerasState


class CommitStateCallback(keras.callbacks.Callback):
    """Commit the elastic state every ``batches_per_commit`` batches
    and at each epoch end (parity: ``hvd.elastic.CommitStateCallback``
    / ``CommitStateCallbackImpl`` in horovod/_keras/elastic.py; the
    epoch-end commit is an addition so the final epoch of a fit is
    never lost).  ``batches_per_commit=0`` disables the per-batch
    commits (reference semantics), leaving only the epoch-end ones.
    A commit is the rollback point for failure recovery and the
    boundary where a pending host update interrupts
    (``HostsUpdatedInterrupt``), so committing more often trades
    commit overhead for less lost work.  Order this AFTER the
    ``Update*StateCallback``s in the callbacks list so each commit
    captures the already-updated batch/epoch counters."""

    def __init__(self, state, batches_per_commit: int = 1):
        super().__init__()
        self.state = state
        self.batches_per_commit = batches_per_commit
        self._remaining = batches_per_commit

    def on_batch_end(self, batch, logs=None):
        if self.batches_per_commit <= 0:
            return
        self._remaining -= 1
        if self._remaining <= 0:
            self._remaining = self.batches_per_commit
            steps = (self.params or {}).get("steps")
            if steps is not None and batch + 1 >= steps:
                # the epoch's final batch: skip — the epoch-end commit
                # below snapshots the same weights WITH the updated
                # epoch/batch counters (the Update*StateCallbacks run
                # first), so committing here would only duplicate the
                # full deep-copy/pickle
                return
            self.state.commit()

    def on_epoch_end(self, epoch, logs=None):
        self._remaining = self.batches_per_commit
        self.state.commit()


class UpdateBatchStateCallback(keras.callbacks.Callback):
    """Track the in-epoch batch number on the state (parity:
    ``hvd.elastic.UpdateBatchStateCallback``); resets to 0 at each
    epoch end.

    Resume granularity under ``model.fit``: Keras 3's fit loop owns
    its iterator, so a restore mid-epoch cannot skip the
    already-consumed batches — fit resumes at EPOCH granularity
    (``initial_epoch=state.epoch``) and replays the interrupted epoch
    from its start (this callback logs that and re-zeros
    ``state.batch`` so in-epoch commits renumber correctly).  Custom
    training loops get true batch-granular resume by starting their
    step range at ``state.batch``."""

    def __init__(self, state):
        super().__init__()
        self.state = state

    def on_epoch_begin(self, epoch, logs=None):
        if self.state.batch > 0 and epoch == self.state.epoch:
            import logging

            logging.getLogger("horovod_tpu").warning(
                "elastic resume: epoch %d replays from its start "
                "(%d batches were already consumed before the reset; "
                "keras fit cannot skip into an epoch)",
                epoch, self.state.batch)
            self.state.batch = 0

    def on_train_batch_end(self, batch, logs=None):
        self.state.batch = batch + 1

    def on_epoch_end(self, epoch, logs=None):
        self.state.batch = 0


class UpdateEpochStateCallback(keras.callbacks.Callback):
    """Keep ``state.epoch`` current so a restarted worker resumes from
    the right ``initial_epoch`` (parity:
    ``hvd.elastic.UpdateEpochStateCallback``)."""

    def __init__(self, state):
        super().__init__()
        self.state = state

    def on_epoch_end(self, epoch, logs=None):
        self.state.epoch = epoch + 1
