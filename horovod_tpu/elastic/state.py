"""Elastic state objects: commit / restore / sync.

Parity surface: ``horovod/common/elastic.py`` (``State``, ``ObjectState``)
— user-visible training state that can be committed at batch boundaries,
rolled back after a failure, and synchronized to newly-joined workers.

TPU-native departure (SURVEY.md §7.2 hard part 3): the JAX coordination
service cannot resize a live world, so elastic reconfiguration is
**restart-based**: the elastic driver relaunches workers on membership
change, and ``commit()`` therefore persists a snapshot to a durable
per-job directory (``HVTPU_ELASTIC_STATE_DIR``, set by the driver) in
addition to the in-memory copy the reference keeps.  ``sync()`` after a
restart loads the newest committed snapshot and broadcasts it from the
lowest rank that has one — the "checkpoint-based resync" idiom the
survey prescribes for TPU slices (orbax-style rank-0 checkpointing).
"""

from __future__ import annotations

import copy
import itertools
import logging
import os
import pickle
from typing import Any, Callable, Dict, List, Optional

from ..core import durable as core_durable
from ..core import state as core_state
from ..core.exceptions import HostsUpdatedInterrupt

logger = logging.getLogger("horovod_tpu")


def _state_dir() -> Optional[str]:
    return os.environ.get("HVTPU_ELASTIC_STATE_DIR") or None


def _commit_path(dirname: str) -> str:
    """Pre-durable-plane single-pickle location — READ-side compat
    only: sync() falls back to it when a state dir holds no manifest-
    committed snapshots (a job upgraded mid-flight)."""
    return os.path.join(dirname, "state_commit.pkl")


#: Restore-quorum round counter: every rank calls sync() the same
#: number of times (the collective contract), so a per-process counter
#: yields matching namespaces without any extra coordination.
_quorum_round = itertools.count()


def _quorum_kv(st):
    """The coordination KV for the restore quorum, wrapped in the
    retry + fencing planes — None when no coordination service is up
    (single-process runs, unit tests).  Fenced so a superseded zombie
    cannot cast a stale vote, and journaled (core/journal.py via the
    process-wide journal) so a coordinator-loss relaunch replays the
    votes this rank already cast."""
    if not core_state._coordination_client_active():
        return None
    try:
        from jax._src import distributed as _jd

        client = _jd.global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None
    if client is None:
        return None
    from ..core.journal import default_journal
    from ..core.retry import fenced_kv

    return fenced_kv(client, rank=st.rank,
                     journal=default_journal(st.rank))


def _flush_durable_writes() -> None:
    """Drain the background writer before any restore-side read: a
    snapshot still in the queue is not yet on disk, and a write error
    must surface before we decide what the latest durable commit is."""
    try:
        core_durable.shared_writer().flush()
    except RuntimeError:
        logger.warning("elastic state: background durable write failed; "
                       "restoring from the last verified commit",
                       exc_info=True)


class State:
    """Base elastic state (parity: horovod/common/elastic.py State).

    Subclasses implement ``save``/``restore_impl``/``sync_impl`` over
    their payload; this base owns commit bookkeeping, reset callbacks,
    and the host-update check raised at commit boundaries.
    """

    def __init__(self):
        self._reset_callbacks: List[Callable[[], None]] = []
        self._host_messages = _HostUpdateFlag.instance()
        self._synced = False
        self._commit_count = 0
        self._durable_every = 1

    def register_reset_callbacks(self, callbacks):
        """Parity: State.register_reset_callbacks — called after a world
        reconfiguration so the user can rebuild derived objects
        (e.g. learning-rate schedules that depend on world size)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._synced = False
        for cb in self._reset_callbacks:
            cb()

    def set_commit_policy(self, every_n_commits: int = 1):
        """Throttle the DURABLE half of ``commit()`` to every Nth call.

        The in-memory snapshot (rollback target for
        ``HorovodInternalError`` recovery) still happens on every
        commit; only the disk write — which at pod scale writes every
        rank's shards (``ShardedJaxState``) — is skipped between
        multiples.  Trade-off: a crash-and-RELAUNCH resumes from the
        last *durable* commit, up to N-1 commits back.  The decision is
        a deterministic function of the commit count, hence identical
        on every rank — a wall-clock policy would desync the collective
        sharded write.  Call ``save()`` directly for an unconditional
        durable snapshot (e.g. right before a planned exit).
        """
        if not isinstance(every_n_commits, int) \
                or isinstance(every_n_commits, bool) \
                or every_n_commits < 1:
            raise ValueError(
                f"every_n_commits must be an int >= 1, got "
                f"{every_n_commits!r}")
        self._durable_every = every_n_commits

    # True when save() is a COLLECTIVE (every rank participates, e.g.
    # the sharded checkpointer) — such saves may only run at
    # rank-deterministic points, so the pending-resize promotion below
    # must not apply.
    _DURABLE_IS_COLLECTIVE = False

    def commit(self):
        """Snapshot state (memory, and the durable dir per the commit
        policy) then check for host updates (parity: State.commit =
        save + check_host_updates)."""
        # step boundary: the worker.step fault-injection site (a kill
        # here dies BEFORE the snapshot, so recovery resumes from the
        # previous commit — the realistic mid-step death)
        from . import worker as _worker

        _worker.note_step()
        self._commit_count += 1
        # Graceful drain (core/preempt.py): with a preemption notice
        # pending somewhere in the world, ask whether THIS boundary is
        # the agreed drain commit.  The boundary is a commit-count
        # agreement (min over published plans) and commit counts
        # advance in lockstep, so — unlike the SIGUSR1 promotion below
        # — forcing even a COLLECTIVE durable save is safe here.
        from ..core import preempt as _preempt

        drain_now = _preempt.pending() \
            and _preempt.drain_boundary(self._commit_count)
        durable = self._commit_count % self._durable_every == 0
        if drain_now:
            durable = True
        if not durable and self._host_messages.flag \
                and not self._DURABLE_IS_COLLECTIVE:
            # a membership change is about to interrupt this commit —
            # promote to a durable save so the PLANNED resize path
            # loses nothing (rank-local writes only: the signal is not
            # rank-synchronous, so a collective save here could pair
            # with a peer that saw the flag one commit later)
            durable = True
        if durable:
            self.save()
        else:
            self.save_to_memory()
        # Periodic cross-rank divergence audit (core/audit.py): the
        # commit boundary is the one point every rank reaches in
        # lockstep (the elastic contract), so the audit's collective
        # digest exchange is safe here.  The commit count advances
        # identically on every rank — a wall-clock cadence would
        # desync the exchange.  Off unless HVTPU_AUDIT_EVERY > 0.
        from ..core import audit as core_audit

        n = core_audit.audit_every()
        if n > 0 and self._commit_count % n == 0:
            self.audit("elastic.commit")
        if drain_now:
            # the drain commit persisted: the departing rank exits
            # DRAIN_EXIT_CODE here; peers raise DrainInterrupt (the
            # committed state stands — no rollback)
            _preempt.finish_drain(self._commit_count)
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt at a commit boundary if the
        driver signalled a membership change (SIGUSR1-based analog of
        the reference's WorkerNotificationManager)."""
        if self._host_messages.consume():
            raise HostsUpdatedInterrupt(skip_sync=False)

    # -- overridable payload hooks --
    def save_to_memory(self):
        """In-memory-only snapshot (rollback target).  Subclasses
        without a cheaper memory path inherit the full save."""
        self.save()

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def rebroadcast(self):
        """Re-broadcast tracked state from rank 0 WITHOUT touching the
        durable commit.  Called after reset callbacks run in a
        relaunched incarnation: a rank-dependent callback (lr schedules
        derived from rank/world, say) would otherwise leave tracked
        attributes silently diverged across ranks — the reference
        avoids this by ordering callbacks before its sync; here sync
        must come first (it restores the committed payload the
        callbacks read), so the divergence window is closed by a
        second, broadcast-only pass.  Base State tracks nothing."""

    def audit(self, label: str = "elastic.state") -> Optional[dict]:
        """Verify this state is identical on every rank via the
        parameter divergence audit (core/audit.py).  Gated on
        ``HVTPU_AUDIT_EVERY`` > 0: the elastic run wrapper calls this
        after every sync/rebroadcast so each incarnation STARTS from
        verified-identical state; on divergence the configured
        ``HVTPU_AUDIT_ACTION`` applies (abort raises
        ``HvtpuDivergenceError`` → restore + driver relaunch from the
        last commit).  Base State tracks nothing → no-op."""
        return None


class _HostUpdateFlag:
    """Process-wide flag set by the elastic worker signal handler
    (horovod_tpu.elastic.worker installs it); consumed at commit."""

    _inst: Optional["_HostUpdateFlag"] = None

    def __init__(self):
        self.flag = False

    @classmethod
    def instance(cls) -> "_HostUpdateFlag":
        if cls._inst is None:
            cls._inst = cls()
        return cls._inst

    def set(self):
        self.flag = True

    def consume(self) -> bool:
        f, self.flag = self.flag, False
        return f


class ObjectState(State):
    """Elastic state holding arbitrary picklable attributes (parity:
    horovod/common/elastic.py ObjectState): ``state.epoch``,
    ``state.batch`` etc. become tracked attributes."""

    def __init__(self, **kwargs):
        super().__init__()
        self._saved: Dict[str, Any] = {}
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._tracked = list(kwargs)
        self.save_to_memory()

    # -- payload capture --
    # Participant protocol: a tracked value exposing
    # ``hvtpu_state_dict()`` / ``hvtpu_load_state_dict(d)`` (e.g. a
    # data.LoaderState) is captured via its dict and restored IN PLACE,
    # so live objects holding a reference to it (the data loader, its
    # prefetch thread) ride commits/rollbacks without re-registration.
    def _capture(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k in self._tracked:
            v = getattr(self, k)
            if hasattr(v, "hvtpu_state_dict"):
                out[k] = copy.deepcopy(v.hvtpu_state_dict())
            else:
                out[k] = copy.deepcopy(v)
        return out

    def _apply(self, payload: Dict[str, Any]):
        for k, v in payload.items():
            cur = getattr(self, k, None)
            if cur is not None and hasattr(cur, "hvtpu_load_state_dict") \
                    and isinstance(v, dict):
                cur.hvtpu_load_state_dict(v)
            else:
                setattr(self, k, v)

    def save_to_memory(self):
        self._saved = self._capture()

    #: Monotonic durable-commit seq, seeded from disk on first save so
    #: a relaunched incarnation continues the sequence instead of
    #: overwriting the commits it must restore from.
    _ckpt_seq = 0

    def save(self):
        """Durable snapshot through the commit protocol
        (core/durable.py): the payload is pickled HERE — the snapshot-
        to-memory at the boundary — and the disk write (tmp → fsync →
        rename → manifest-last) runs on the background writer unless
        ``HVTPU_CKPT_ASYNC=0``."""
        self.save_to_memory()
        d = _state_dir()
        if d and core_state.global_state().rank == 0:
            os.makedirs(d, exist_ok=True)
            payload = pickle.dumps(self._to_disk_payload())
            if self._ckpt_seq == 0:
                self._ckpt_seq = max(
                    core_durable.list_snapshots(d), default=0)
            self._ckpt_seq += 1
            seq = self._ckpt_seq

            def _write() -> None:
                core_durable.write_snapshot(
                    d, seq, {"state.pkl": payload})

            if core_durable._async_enabled():
                core_durable.shared_writer().submit(_write)
            else:
                _write()

    def wait_durable(self):
        """Block until every queued background durable write is on
        disk; re-raises a captured write error.  The drain and reset
        exits quiesce the writer themselves — this is for callers that
        need read-your-writes (tests, external checkpoint shippers)."""
        core_durable.shared_writer().flush()

    def restore(self):
        """Roll back to the last commit (parity: State.restore after
        HorovodInternalError)."""
        self._apply(copy.deepcopy(self._saved))
        self.on_reset()

    def _quorum_agree(self, local_best: Optional[int]) -> Optional[int]:
        """Min-agree ``local_best`` across ranks over the coordination
        KV.  A quorum failure degrades to this rank's local best —
        safe because only rank 0's pick is loaded and its broadcast
        carries the payload to everyone (divergence-impossible by
        construction; the quorum exists so rank 0 never picks a commit
        a peer does not have durable)."""
        st = core_state.global_state()
        if st.size <= 1:
            return local_best
        kv = _quorum_kv(st)
        round_no = next(_quorum_round)
        if kv is None:
            return local_best
        gen = os.environ.get("HVTPU_ELASTIC_GENERATION", "0") or "0"
        try:
            return core_durable.restore_quorum(
                kv, rank=st.rank, size=st.size, local_best=local_best,
                namespace=f"hvtpu/ckpt/quorum/{gen}/{round_no}")
        except Exception:  # noqa: BLE001 — degrade, never diverge
            logger.warning(
                "elastic state: restore quorum failed; falling back to "
                "this rank's local best commit", exc_info=True)
            return local_best

    def _agree_restore_seq(self, d: str) -> Optional[int]:
        """The restore point: each rank's highest locally-VERIFIED
        commit (torn/corrupt snapshots discarded by manifest
        verification), min-agreed across ranks."""
        _flush_durable_writes()
        return self._quorum_agree(core_durable.latest_verified(d))

    def sync(self):
        """Make every rank identical: after a restart, agree on the
        restore commit (verify manifests, discard torn/corrupt
        snapshots, KV quorum on the highest commit durable
        EVERYWHERE), load it on rank 0, then broadcast rank 0's
        payload (parity: ObjectState.sync broadcasting from rank 0)."""
        from ..api import functions as api_functions

        st = core_state.require_init("elastic state sync")
        d = _state_dir()
        if d and not self._synced:
            agreed = self._agree_restore_seq(d)
            if st.rank == 0:
                if agreed is not None:
                    files = core_durable.read_snapshot(d, agreed)
                    self._from_disk_payload(
                        pickle.loads(files["state.pkl"]))
                elif os.path.exists(_commit_path(d)):
                    # pre-durable-plane layout (job upgraded mid-run)
                    with open(_commit_path(d), "rb") as f:
                        self._from_disk_payload(pickle.load(f))
        payload = api_functions.broadcast_object(
            self._capture(), root_rank=0
        )
        self._apply(payload)
        self.save_to_memory()
        self._synced = True

    def rebroadcast(self):
        """Broadcast-only re-sync of tracked attributes from rank 0
        (no disk load, ``_synced`` untouched) — see State.rebroadcast."""
        from ..api import functions as api_functions

        core_state.require_init("elastic state rebroadcast")
        payload = api_functions.broadcast_object(
            self._capture(), root_rank=0
        )
        self._apply(payload)
        self.save_to_memory()

    def audit(self, label: str = "elastic.state") -> Optional[dict]:
        """Cross-rank digest audit of the tracked attributes (see
        State.audit); collective when it runs, so the gating env var
        must agree on every rank (the launcher distributes it)."""
        from ..core import audit as core_audit

        if core_audit.audit_every() <= 0:
            return None
        return core_audit.verify(self._capture(), label)

    # -- disk representation hooks (subclasses with non-picklable
    #    payloads override these) --
    def _to_disk_payload(self):
        return self._capture()

    def _from_disk_payload(self, payload):
        self._apply(payload)


class JaxState(ObjectState):
    """Elastic state for JAX training loops: tracked attributes may be
    pytrees of jax Arrays (params, optimizer state) alongside plain
    Python scalars.  TPU-native analog of the reference's per-framework
    TorchState/TensorFlowKerasState.

    Arrays are pulled to host numpy for the durable snapshot so a
    restarted world (possibly a different device count) can load it.
    NOTE: that requires fully-addressable arrays (single-controller /
    per-process values); for GLOBAL arrays spanning processes (the pod
    shape) use :class:`ShardedJaxState`, whose durable commit writes
    each process's shards and reshards on resync.
    """

    def _to_disk_payload(self):
        import jax
        import numpy as np

        return jax.tree.map(
            lambda x: np.asarray(x) if hasattr(x, "dtype") else x,
            self._capture(),
        )

    def _from_disk_payload(self, payload):
        import jax.numpy as jnp

        def back(x):
            import numpy as np

            return jnp.asarray(x) if isinstance(x, np.ndarray) else x

        import jax

        self._apply(jax.tree.map(back, payload))


class ShardedJaxState(JaxState):
    """Elastic state for the POD SHAPE: tracked attributes may hold
    GLOBAL ``jax.Array``s sharded across processes.

    ``JaxState``'s durable path (``np.asarray`` per leaf) raises on a
    non-fully-addressable global array; here the durable commit rides
    :class:`~horovod_tpu.api.sharded_checkpoint.ShardedCheckpointer`
    instead — every process writes its own shards, and ``sync()`` after
    an elastic restart reassembles each leaf onto the NEW world's
    shardings (taken from the freshly-initialized attribute values the
    restarted trainer constructed, which serve as the restore
    template).  Non-array attributes keep the rank-0 pickle +
    broadcast path.

    Commit is collective (all ranks call ``commit()`` at the same
    boundary — already the elastic contract); the newest
    ``HVTPU_CKPT_KEEP`` commits are retained.
    """

    # every process writes its shards: the durable save is collective,
    # so the commit policy may not promote it at a pending resize (the
    # SIGUSR1 flag is not rank-synchronous)
    _DURABLE_IS_COLLECTIVE = True

    def _sharded_dir(self) -> Optional[str]:
        d = _state_dir()
        return os.path.join(d, "sharded") if d else None

    def _split(self, payload: Dict[str, Any]):
        """(array_attrs, plain_attrs): an attribute whose pytree holds
        any jax.Array goes through the sharded checkpointer (its
        host-leaf path covers mixed trees); the rest ride pickle."""
        import jax

        arrays, rest = {}, {}
        for k, v in payload.items():
            if any(isinstance(leaf, jax.Array)
                   for leaf in jax.tree_util.tree_leaves(v)):
                arrays[k] = v
            else:
                rest[k] = v
        return arrays, rest

    def save(self):
        self.save_to_memory()
        d = self._sharded_dir()
        if not d:
            return
        from ..api.sharded_checkpoint import ShardedCheckpointer

        st = core_state.require_init("elastic sharded commit")
        # split the snapshot save_to_memory already deep-copied — a
        # second _capture() would transiently duplicate every global
        # device array at the commit boundary (ShardedCheckpointer
        # only reads, so sharing the snapshot is safe)
        arrays, rest = self._split(self._saved)
        ckpt = ShardedCheckpointer(d)
        # Rank 0 ALONE picks the step number and broadcasts it: a
        # per-rank latest_step() is a shared-filesystem directory
        # listing, and NFS attribute/dircache skew can make ranks
        # disagree — shards then land in different step_NNN dirs and
        # the committed step is incomplete (same hazard class sync()
        # guards against with its rank-0-decides branch).
        from ..api import functions as api_functions

        if st.rank == 0:
            step = (ckpt.latest_step() or 0) + 1
        else:
            step = None
        step = api_functions.broadcast_object(step, root_rank=0)
        ckpt.save(step, arrays)
        if st.rank == 0:
            # the rest-pickle commits through the durable protocol as
            # snapshot seq == step: its manifest-last rename is what
            # makes step N restorable (the shard write above already
            # barriered, so a commit here is never ahead of its pieces)
            payload = pickle.dumps({"step": step, "rest": rest,
                                    "array_attrs": sorted(arrays)})
            core_durable.write_snapshot(
                _state_dir(), step, {"sharded_rest.pkl": payload})
            # retention: drop shard steps beyond the HVTPU_CKPT_KEEP
            # window (write_snapshot already GC'd the rest commits)
            import shutil

            keep = core_durable._keep()
            for s in ckpt.all_steps()[:-keep]:
                shutil.rmtree(ckpt._step_dir(s), ignore_errors=True)

    def _local_best_sharded(self, d: str) -> Optional[int]:
        """Highest step whose rest-commit AND this rank's view of the
        sharded pieces both pass manifest verification — each rank
        vouches for the shards it can actually read, which is exactly
        what the quorum needs to agree on a step restorable
        EVERYWHERE."""
        from ..api.sharded_checkpoint import ShardedCheckpointer

        ckpt = ShardedCheckpointer(d)
        root = _state_dir()
        for seq in reversed(core_durable.list_snapshots(root)):
            if not core_durable.verify_snapshot(
                    core_durable.snapshot_path(root, seq)):
                continue
            if ckpt.verify_step(seq):
                return seq
        return None

    def sync(self):
        from ..api import functions as api_functions
        from ..api.sharded_checkpoint import ShardedCheckpointer

        st = core_state.require_init("elastic state sync")
        d = self._sharded_dir()
        # Every rank verifies its own view and votes; rank 0 ALONE
        # loads the agreed step and broadcasts the decision: a
        # per-rank os.path.exists over a shared filesystem can
        # disagree across hosts (NFS attribute caches), and divergent
        # branches would desync the collective sequence — some ranks
        # inside the restore's make_array_from_callback, others not.
        agreed = None
        if d and not self._synced:
            _flush_durable_writes()
            agreed = self._quorum_agree(self._local_best_sharded(d))
        if st.rank == 0:
            disk = None
            if d and not self._synced:
                if agreed is not None:
                    files = core_durable.read_snapshot(
                        _state_dir(), agreed)
                    disk = pickle.loads(files["sharded_rest.pkl"])
                elif os.path.exists(_commit_path(_state_dir())):
                    # pre-durable-plane layout (job upgraded mid-run)
                    with open(_commit_path(_state_dir()), "rb") as f:
                        disk = pickle.load(f)
            msg = {"disk": disk}
        else:
            msg = None
        msg = api_functions.broadcast_object(msg, root_rank=0)
        disk = msg["disk"]
        if disk is not None:
            self._apply(disk["rest"])
            # current attribute values carry the NEW world's shardings:
            # use them as the restore template
            arrays, _ = self._split(self._capture())
            # every array attr the saver committed must be restorable
            # through the template — a fresh value that is not a
            # jax.Array tree (e.g. params=None placeholder) would
            # silently keep its uninitialized state otherwise
            missing = set(disk.get("array_attrs", [])) - set(arrays)
            if missing:
                raise ValueError(
                    "ShardedJaxState.sync: committed array attributes "
                    f"{sorted(missing)} have no jax.Array template in "
                    "the restarted state; construct them (device_put "
                    "with the new mesh's sharding) before sync()"
                )
            restored = ShardedCheckpointer(d).restore(
                arrays, step=disk["step"]
            )
            self._apply(restored)
        else:
            # no durable commit: plain-attr broadcast only; global
            # arrays are already identical by SPMD construction
            _, rest = self._split(self._capture())
            payload = api_functions.broadcast_object(rest, root_rank=0)
            self._apply(payload)
        self.save_to_memory()
        self._synced = True

    def rebroadcast(self):
        """Plain-attribute broadcast only: global jax.Arrays are not
        picklable across processes, and a reset callback that rebuilt
        one did so through the SPMD collectives — identical by
        construction."""
        from ..api import functions as api_functions

        core_state.require_init("elastic state rebroadcast")
        _, rest = self._split(self._capture())
        payload = api_functions.broadcast_object(rest, root_rank=0)
        self._apply(payload)
        self.save_to_memory()

    def audit(self, label: str = "elastic.state") -> Optional[dict]:
        """Audit the REPLICATED half only: global arrays are sharded —
        each rank legitimately holds a different piece (and pulling a
        non-addressable array to host raises), so cross-rank digests of
        shards would be a false divergence.  Plain attributes must
        still agree everywhere."""
        from ..core import audit as core_audit

        if core_audit.audit_every() <= 0:
            return None
        _, rest = self._split(self._capture())
        return core_audit.verify(rest, label)
