"""Unified metrics registry (obs/metrics.py): registry semantics,
Prometheus exposition (text format + HTTP endpoint), cross-rank
aggregation over the coordination KV, and the instrumentation hooks in
the hot layers (controller cycle marks, stall counters, step rates).

The acceptance shape mirrors the reference's always-on telemetry goal:
with HVTPU_METRICS_PORT set, a live 2-process CPU job must answer an
HTTP GET with nonzero collective counters; with it unset, the registry
must stay a sub-microsecond dict update (idle-cost test).
"""

import json
import os
import threading
import time
import urllib.request

import pytest

import horovod_tpu
from horovod_tpu.obs import metrics
from horovod_tpu.obs.metrics import MetricsRegistry
from horovod_tpu.runner import run

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}


# --------------------------------------------------------------------------
# registry unit tests
# --------------------------------------------------------------------------

class TestRegistry:
    def test_counter_monotonic_and_labeled(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "help")
        c.inc()
        c.inc(2.5)
        c.inc(1, op="allreduce")
        assert c.value() == 3.5
        assert c.value(op="allreduce") == 1
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_family_idempotent_and_kind_clash(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total")
        assert reg.counter("x_total") is a
        with pytest.raises(TypeError):
            reg.gauge("x_total")

    def test_gauge_set_inc_dec(self):
        reg = MetricsRegistry()
        g = reg.gauge("g")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value() == 6

    def test_concurrent_increments_exact(self):
        """N threads hammering one counter lose no increments."""
        reg = MetricsRegistry()
        c = reg.counter("c_total")
        h = reg.histogram("h_seconds", buckets=[0.5])
        n_threads, per_thread = 8, 5000

        def work():
            for _ in range(per_thread):
                c.inc()
                h.observe(0.1)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == n_threads * per_thread
        assert h.value() == n_threads * per_thread

    def test_histogram_bucketing(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", buckets=[0.1, 1.0, 10.0])
        for v in (0.05, 0.1, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = reg.snapshot()["lat"]
        assert snap["buckets"] == [0.1, 1.0, 10.0]
        cell = snap["values"][""]
        # bisect_left: 0.05 and the exact 0.1 boundary land in le=0.1
        assert cell["counts"] == [2, 1, 1, 1]
        assert cell["count"] == 5
        assert cell["sum"] == pytest.approx(55.65)

    def test_prometheus_text_golden(self):
        """Exact text-format 0.0.4 output for a small registry."""
        reg = MetricsRegistry()
        c = reg.counter("hvt_ops_total", "Ops executed.")
        c.inc(2, op="allreduce")
        reg.gauge("hvt_depth", "Queue depth.").set(1.5)
        h = reg.histogram("hvt_lat", "Latency.", buckets=[0.1, 1.0, 10.0])
        h.observe(0.05)
        h.observe(5.0)
        h.observe(50.0)
        expected = "\n".join([
            "# HELP hvt_depth Queue depth.",
            "# TYPE hvt_depth gauge",
            "hvt_depth 1.5",
            "# HELP hvt_lat Latency.",
            "# TYPE hvt_lat histogram",
            'hvt_lat_bucket{le="0.1"} 1',
            'hvt_lat_bucket{le="1"} 1',
            'hvt_lat_bucket{le="10"} 2',
            'hvt_lat_bucket{le="+Inf"} 3',
            "hvt_lat_sum 55.05",
            "hvt_lat_count 3",
            "# HELP hvt_ops_total Ops executed.",
            "# TYPE hvt_ops_total counter",
            'hvt_ops_total{op="allreduce"} 2',
        ]) + "\n"
        assert reg.exposition() == expected

    def test_snapshot_json_serializable_and_reset(self):
        reg = MetricsRegistry()
        reg.counter("a_total").inc(3)
        reg.histogram("b", buckets=[1.0]).observe(0.5)
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["a_total"]["values"][""] == 3
        reg.reset()
        assert reg.counter("a_total").value() == 0
        assert reg.snapshot()["b"]["values"] == {}

    def test_idle_cost_sanity(self):
        """A counter increment stays a dict-update, not an I/O call:
        generous bound (100 us/op amortized) that only a pathological
        regression (locking the exposition path, syscalls) would trip."""
        reg = MetricsRegistry()
        c = reg.counter("hot_total")
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
        per_op = (time.perf_counter() - t0) / n
        assert c.value() == n
        assert per_op < 100e-6, f"counter.inc cost {per_op * 1e6:.1f} us/op"

    def test_log_buckets(self):
        b = metrics.log_buckets(1e-5, 4.0, 3)
        assert b == pytest.approx((1e-5, 4e-5, 1.6e-4))


class TestMergeSnapshots:
    def test_counters_and_histograms_sum(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        for reg, k in ((r1, 1), (r2, 2)):
            reg.counter("ops_total").inc(k)
            reg.histogram("lat", buckets=[1.0, 10.0]).observe(k)
        merged = metrics.merge_snapshots([r1.snapshot(), r2.snapshot()])
        assert merged["ops_total"]["values"][""] == 3
        cell = merged["lat"]["values"][""]
        assert cell["count"] == 2 and cell["sum"] == 3.0
        # rank 1's 1.0 sits on the le=1 boundary; rank 2's 2.0 in le=10
        assert cell["counts"] == [1, 1, 0]

    def test_bucket_mismatch_rejected(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.histogram("lat", buckets=[1.0]).observe(0.5)
        r2.histogram("lat", buckets=[2.0]).observe(0.5)
        with pytest.raises(ValueError):
            metrics.merge_snapshots([r1.snapshot(), r2.snapshot()])


# --------------------------------------------------------------------------
# exposition endpoint round-trip (localhost)
# --------------------------------------------------------------------------

class TestHttpEndpoint:
    def test_round_trip(self):
        reg = MetricsRegistry()
        reg.counter("rt_total", "Round trip.").inc(7)
        # the module-level server is shared; make sure no stale one
        metrics.stop_http_server()
        port = metrics.start_http_server(0, registry=reg)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                body = r.read().decode()
            assert "rt_total 7" in body
            # scrape twice: the server thread must survive a request
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/", timeout=10) as r:
                assert r.status == 200
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10)
        finally:
            metrics.stop_http_server()

    def test_debug_endpoint_round_trip(self):
        metrics.stop_http_server()
        metrics.register_debug_provider(
            "okprov", lambda: {"depth": 3})
        metrics.register_debug_provider(
            "badprov", lambda: 1 / 0)
        port = metrics.start_http_server(0, registry=MetricsRegistry())
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug", timeout=10) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith(
                    "application/json")
                dbg = json.loads(r.read().decode())
            assert dbg["okprov"] == {"depth": 3}
            # a raising provider is isolated, not a 500
            assert "error" in dbg["badprov"]
            assert dbg["time_unix"] > 0
        finally:
            metrics.stop_http_server()
            metrics.unregister_debug_provider("okprov")
            metrics.unregister_debug_provider("badprov")
        assert "okprov" not in metrics.debug_snapshot()

    def test_serve_from_env_disabled_and_bad_values(self, monkeypatch):
        monkeypatch.delenv("HVTPU_METRICS_PORT", raising=False)
        monkeypatch.delenv("HOROVOD_METRICS_PORT", raising=False)
        assert metrics.serve_from_env() is None
        monkeypatch.setenv("HVTPU_METRICS_PORT", "not-a-port")
        assert metrics.serve_from_env() is None
        monkeypatch.setenv("HVTPU_METRICS_PORT", "0")
        assert metrics.serve_from_env() is None


# --------------------------------------------------------------------------
# instrumentation hooks
# --------------------------------------------------------------------------

class TestHooks:
    def test_note_step_counts_steps_and_examples(self):
        before_steps = metrics.REGISTRY.counter(
            "hvtpu_optimizer_steps_total").value()
        before_ex = metrics.REGISTRY.counter(
            "hvtpu_examples_total").value()
        metrics.note_step(examples=128, steps=4)
        metrics.note_step(examples=128, steps=4)
        assert metrics.REGISTRY.counter(
            "hvtpu_optimizer_steps_total").value() == before_steps + 8
        assert metrics.REGISTRY.counter(
            "hvtpu_examples_total").value() == before_ex + 256
        assert metrics.REGISTRY.gauge(
            "hvtpu_steps_per_second").value() > 0

    def test_eager_allreduce_counts_ops_and_bytes(self, hvt):
        import jax.numpy as jnp

        base_ops = metrics.op_counter("allreduce").value()
        base_bytes = metrics.WIRE_BYTES.value()
        hvt.allreduce(jnp.ones((4,), jnp.float32))
        assert metrics.op_counter("allreduce").value() == base_ops + 1
        # single-process world: nothing crosses the wire
        assert metrics.WIRE_BYTES.value() == base_bytes

    def test_aggregate_single_process_degrades_to_local(self, hvt):
        reg = MetricsRegistry()
        reg.counter("solo_total").inc(5)
        out = metrics.aggregate(registry=reg)
        assert out["merged"]["solo_total"]["values"][""] == 5
        assert list(out["per_rank"]) == [0]


# --------------------------------------------------------------------------
# mark_cycle regression (satellite): CYCLE instants must reach the trace
# --------------------------------------------------------------------------

def test_mark_cycle_emits_cycle_instant(tmp_path):
    """Controller + Timeline(mark_cycles=True): the dead-path bug probed
    ``mark_cycles`` as an attribute that didn't exist and called
    ``mark_cycle()`` without its cycle index — a trace written through a
    live controller must now contain CYCLE instants."""
    from horovod_tpu.eager.controller import EagerController, KVTransport
    from horovod_tpu.obs.timeline import Timeline
    from tests.test_eager_controller import FakeKV

    trace_file = tmp_path / "trace.json"
    tl = Timeline(str(trace_file), rank=0, mark_cycles=True)
    assert tl.mark_cycles is True
    kv = FakeKV()
    ctrl = EagerController(
        0, 1,
        transport=KVTransport(0, 1, client=kv, timeout_s=20.0),
        cycle_time_ms=0.5,
        timeline=tl,
    )
    ctrl.start()
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if metrics.REGISTRY.counter(
                    "hvtpu_controller_cycles_total").value() > 2:
                break
            time.sleep(0.01)
    finally:
        ctrl.request_shutdown()
        ctrl.stop()
        tl.close()
    events = json.loads(trace_file.read_text())
    cycles = [e for e in events if e.get("name") == "CYCLE"
              and e.get("ph") == "i"]
    assert cycles, "no CYCLE instant in the written trace"
    assert "index" in cycles[0]["args"]


def test_timeline_begin_ends_open_span(tmp_path):
    """Phase transitions (NEGOTIATE -> QUEUE) must close the previous
    span: every B needs a matching E, in nesting order."""
    from horovod_tpu.obs.timeline import Timeline

    trace_file = tmp_path / "trace.json"
    tl = Timeline(str(trace_file), rank=0)
    tl.begin("t0", "NEGOTIATE_ALLREDUCE")
    tl.begin("t0", "QUEUE")
    tl.begin("t0", "ICI_ALLREDUCE")
    tl.end("t0")
    tl.close()
    events = json.loads(trace_file.read_text())
    spans = [(e["ph"], e["name"]) for e in events
             if e.get("cat") == "tensor"]
    assert spans == [
        ("B", "NEGOTIATE_ALLREDUCE"), ("E", "NEGOTIATE_ALLREDUCE"),
        ("B", "QUEUE"), ("E", "QUEUE"),
        ("B", "ICI_ALLREDUCE"), ("E", "ICI_ALLREDUCE"),
    ]


# --------------------------------------------------------------------------
# cross-rank aggregation + live exposition endpoint (2 real processes)
# --------------------------------------------------------------------------

@pytest.mark.multiprocess
def test_aggregate_2proc():
    """aggregate() allgathers per-rank snapshots over the coordination
    KV; both ranks get the identical merged view."""

    def body():
        import horovod_tpu as hvt
        from horovod_tpu.obs import metrics as m

        hvt.init()
        r = hvt.rank()
        m.REGISTRY.counter("agg_test_total").inc(r + 1)
        m.REGISTRY.histogram(
            "agg_test_seconds", buckets=[1.0, 10.0]).observe(r + 1)
        out = m.aggregate()
        assert sorted(out["per_rank"]) == [0, 1]
        merged = out["merged"]
        assert merged["agg_test_total"]["values"][""] == 3
        cell = merged["agg_test_seconds"]["values"][""]
        assert cell["count"] == 2 and cell["sum"] == 3.0
        assert out["per_rank"][1]["agg_test_total"]["values"][""] == 2
        # a second round must not collide with the first's KV keys
        out2 = m.aggregate()
        assert out2["merged"]["agg_test_total"]["values"][""] == 3
        hvt.shutdown()
        return "ok"

    assert run(body, np=2, cpu_devices=1, env=_ENV,
               start_timeout=300.0) == ["ok", "ok"]


@pytest.mark.multiprocess
def test_metrics_endpoint_live_2proc():
    """Acceptance shape: with HVTPU_METRICS_PORT set, an HTTP GET
    during a 2-process CPU run returns Prometheus text including
    nonzero collective counters, wire bytes, a cycle-duration
    histogram, and the elastic worker-count gauge."""

    def body():
        import urllib.request

        import jax.numpy as jnp

        import horovod_tpu as hvt

        hvt.init()
        r = hvt.rank()
        # sync plane: counted by _record_collective
        hvt.allreduce(jnp.ones((1024,), jnp.float32))
        # async plane: drives controller cycles (cycle histogram)
        h = hvt.allreduce_async(jnp.full((8,), float(r)))
        hvt.synchronize(h)
        port = 19650 + hvt.local_rank()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            assert resp.status == 200
            body_txt = resp.read().decode()
        lines = body_txt.splitlines()

        def sample(name):
            for ln in lines:
                if ln.startswith(name + " "):
                    return float(ln.split()[-1])
            return None

        assert sample("hvtpu_allreduce_total") >= 2
        assert sample("hvtpu_wire_bytes_total") > 0
        assert sample("hvtpu_tensor_bytes_total") >= 4096
        assert sample("hvtpu_elastic_workers") == 2
        assert "# TYPE hvtpu_controller_cycle_seconds histogram" \
            in body_txt
        assert sample("hvtpu_controller_cycle_seconds_count") > 0
        hvt.shutdown()
        return "ok"

    env = dict(_ENV, HVTPU_METRICS_PORT="19650")
    assert run(body, np=2, cpu_devices=1, env=env,
               start_timeout=300.0) == ["ok", "ok"]
