"""Eager API surface tests (single-process world, P=1 semantics) plus
async-handle behavior — parity targets: horovod/torch/mpi_ops.py eager
ops and handle_manager synchronize/poll.

Multi-process eager behavior is covered by the runner-launched tests
(test_multiprocess.py) which spawn real worker processes, the analog of
the reference's horovodrun-под tests.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import horovod_tpu


class TestEagerSingleProcess:
    def test_allreduce_identity(self, hvt):
        x = jnp.arange(6.0).reshape(2, 3)
        out = hvt.allreduce(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_allreduce_scales(self, hvt):
        x = jnp.ones((4,))
        out = hvt.allreduce(x, prescale_factor=2.0, postscale_factor=3.0)
        np.testing.assert_allclose(np.asarray(out), np.full((4,), 6.0))

    def test_grouped_allreduce(self, hvt):
        outs = hvt.grouped_allreduce([jnp.ones((2,)), jnp.full((3,), 2.0)])
        assert len(outs) == 2
        np.testing.assert_allclose(np.asarray(outs[1]), np.full((3,), 2.0))

    def test_allgather(self, hvt):
        x = jnp.ones((3, 2))
        out = hvt.allgather(x)
        assert out.shape == (3, 2)

    def test_broadcast(self, hvt):
        x = jnp.arange(4.0)
        out = hvt.broadcast(x, root_rank=0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_alltoall_bare_return_without_splits(self, hvt):
        # reference convention: no splits → bare tensor
        x = jnp.arange(6.0).reshape(6, 1)
        out = hvt.alltoall(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_alltoall_tuple_return_with_splits(self, hvt):
        x = jnp.arange(6.0).reshape(6, 1)
        out, splits = hvt.alltoall(x, splits=[6])
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))
        assert np.asarray(splits).tolist() == [6]

    def test_reducescatter(self, hvt):
        x = jnp.ones((4, 2))
        out = hvt.reducescatter(x)
        assert out.shape == (4, 2)

    def test_barrier_and_join(self, hvt):
        hvt.barrier()
        assert hvt.join() == 0

    def test_async_and_synchronize(self, hvt):
        h = hvt.allreduce_async(jnp.ones((2,)))
        # Truly async now (reference semantics): poll flips to True once
        # the background cycle completes the op; synchronize blocks.
        out = hvt.synchronize(h)
        assert hvt.poll(h)  # completed handles poll True
        np.testing.assert_allclose(np.asarray(out), np.ones((2,)))
        with pytest.raises(ValueError):
            hvt.synchronize(h)  # double-sync of same handle


class TestStateDistribution:
    def test_broadcast_parameters_roundtrip(self, hvt):
        params = {"w": jnp.ones((2, 2)), "b": jnp.zeros((2,))}
        out = hvt.broadcast_parameters(params, root_rank=0)
        np.testing.assert_allclose(np.asarray(out["w"]), np.ones((2, 2)))

    def test_broadcast_object(self, hvt):
        obj = {"epoch": 3, "names": ["a", "b"]}
        assert hvt.broadcast_object(obj, root_rank=0) == obj

    def test_allgather_object(self, hvt):
        assert hvt.allgather_object({"r": 0}) == [{"r": 0}]
