// C ABI surface for ctypes bindings (horovod_tpu/native/core.py).
//
// Parity: the reference exposes its C++ core to Python through the
// per-framework pybind modules (horovod/torch/mpi_ops_v2.cc,
// horovod/common/basics.py ctypes on the shared lib).  We expose a
// framework-neutral C ABI and bind it once with ctypes — no pybind11 in
// this environment (see repo constraints).
//
// Memory protocol: functions that return variable-size blobs take a
// caller buffer + capacity and return the needed size; callers retry
// with a bigger buffer if needed (Python wrapper handles this).
#include <cstring>
#include <sstream>

#include "controller.h"
#include "thread_pool.h"
#include "timeline.h"

using namespace hvt;

namespace {

// Controller + staged blobs.  Drain/compute are side-effecting, so the
// two-call size-probe protocol stages the produced blob on the first
// (buf == nullptr) call and only copies it out on the second.
struct ControllerHandle {
  Controller ctrl;
  std::vector<uint8_t> staged_requests;
  std::vector<uint8_t> staged_responses;
  std::vector<uint8_t> staged_predict;
  std::vector<uint8_t> staged_stalls;
  template <typename... A>
  explicit ControllerHandle(A&&... a) : ctrl(std::forward<A>(a)...) {}
};

ControllerHandle* Handle(void* h) { return static_cast<ControllerHandle*>(h); }

Controller* Ctrl(void* h) { return &static_cast<ControllerHandle*>(h)->ctrl; }

// Two-call protocol helper: produce() is only invoked when staging.
template <typename Produce>
int64_t Staged(std::vector<uint8_t>* staged, uint8_t* buf, int64_t cap,
               Produce produce) {
  if (buf == nullptr) {
    *staged = produce();
    return static_cast<int64_t>(staged->size());
  }
  int64_t n = static_cast<int64_t>(staged->size());
  if (cap < n) return n;  // too small: keep staged so the caller can retry
  if (n > 0) memcpy(buf, staged->data(), n);
  staged->clear();
  return n;
}

}  // namespace

extern "C" {

// ---- versioning ----------------------------------------------------------
// v2: + hvt_gp_* (gaussian_process.cc)
// v3: wire v3 cache_bits bypass frame + hvt_controller_set_resync_every
// v4: cross-rank mismatch diagnostics (named-rank error responses +
//     forced cache resync on disagreement)
// v5: wire v5 atomic burst units (burst_id/burst_len delimiter,
//     predicted confirmation flag, confirm_hashes) +
//     hvt_controller_drain_requests gains a limit argument +
//     hvt_controller_force_resync (mispredict re-anchor)
int hvt_abi_version() { return 5; }

// ---- controller ----------------------------------------------------------
void* hvt_controller_new(int rank, int size, int64_t fusion_threshold,
                         int64_t cache_capacity, double stall_warn_s,
                         double stall_abort_s) {
  return new ControllerHandle(rank, size, fusion_threshold,
                              static_cast<size_t>(cache_capacity),
                              stall_warn_s, stall_abort_s);
}

void hvt_controller_free(void* c) {
  delete static_cast<ControllerHandle*>(c);
}

// Returns 0 on success, nonzero on error (duplicate name).
int hvt_controller_enqueue(void* c, uint64_t seq, const char* name,
                           int op_type, int red_op, int dtype,
                           const int64_t* shape, int ndim,
                           int process_set_id, int64_t group_id,
                           int root_rank) {
  Entry e;
  e.seq = seq;
  e.name = name;
  e.type = static_cast<OpType>(op_type);
  e.red_op = static_cast<RedOp>(red_op);
  e.dtype = static_cast<DataType>(dtype);
  e.shape.assign(shape, shape + ndim);
  e.process_set_id = process_set_id;
  e.group_id = group_id;
  e.root_rank = root_rank;
  Status st;
  Ctrl(c)->Enqueue(std::move(e), &st);
  return st.ok ? 0 : 1;
}

void hvt_controller_declare_group(void* c, int64_t group_id, int size) {
  Ctrl(c)->DeclareGroup(group_id, size);
}

void hvt_controller_register_process_set(void* c, int psid,
                                         const int32_t* ranks, int n) {
  Ctrl(c)->RegisterProcessSet(
      psid, std::vector<int32_t>(ranks, ranks + n));
}

void hvt_controller_set_joined(void* c) {
  Ctrl(c)->SetJoined();
}

// limit > 0 caps the drained entries at the caller's known steady
// burst size (atomic-burst cap; 0 = drain everything).
int64_t hvt_controller_drain_requests(void* c, uint8_t* buf, int64_t cap,
                                      int64_t limit) {
  return Staged(&Handle(c)->staged_requests, buf, cap,
                [c, limit] { return Ctrl(c)->DrainRequests(limit); });
}

void hvt_controller_ingest(void* c, const uint8_t* data, int64_t len) {
  Ctrl(c)->Ingest(data, static_cast<size_t>(len));
}

int64_t hvt_controller_compute_responses(void* c, uint8_t* buf, int64_t cap) {
  return Staged(&Handle(c)->staged_responses, buf, cap,
                [c] { return Ctrl(c)->ComputeResponses(); });
}

// Applies responses; writes up to `cap` finished seq ids into out_seqs.
// Returns the number of finished seqs (callers size out_seqs generously:
// one per outstanding handle).
int64_t hvt_controller_apply_responses(void* c, const uint8_t* data,
                                       int64_t len, uint64_t* out_seqs,
                                       int64_t cap) {
  std::vector<uint64_t> fin;
  Ctrl(c)->ApplyResponses(data, static_cast<size_t>(len),
                                              &fin);
  int64_t n = static_cast<int64_t>(fin.size());
  for (int64_t i = 0; i < n && i < cap; ++i) out_seqs[i] = fin[i];
  return n;
}

int64_t hvt_controller_pending_count(void* c) {
  return Ctrl(c)->pending_count();
}

int64_t hvt_controller_pending_bytes(void* c) {
  return Ctrl(c)->pending_bytes();
}

int64_t hvt_controller_cache_size(void* c) {
  return static_cast<int64_t>(Ctrl(c)->cache_size());
}

void hvt_controller_set_fusion_threshold(void* c, int64_t bytes) {
  Ctrl(c)->set_fusion_threshold(bytes);
}

void hvt_controller_set_tuned(void* c, int64_t fusion_threshold,
                              int32_t cycle_time_us) {
  Ctrl(c)->SetTuned(fusion_threshold, cycle_time_us);
}

void hvt_controller_set_shutdown(void* c) { Ctrl(c)->SetShutdown(); }

void hvt_controller_set_resync_every(void* c, int64_t n) {
  Ctrl(c)->SetResyncEvery(n);
}

void hvt_controller_force_resync(void* c) { Ctrl(c)->ForceResync(); }

// Steady-state schedule prediction (two-call size-probe protocol like
// drain/compute).  Returns 0 when a bit is unknown (caller must not
// predict); a real empty ResponseList still serializes to >0 bytes.
int64_t hvt_controller_predict_responses(void* c, const uint32_t* bits,
                                         int64_t n, uint8_t* buf,
                                         int64_t cap) {
  return Staged(&Handle(c)->staged_predict, buf, cap, [c, bits, n] {
    return Ctrl(c)->PredictResponses(
        std::vector<uint32_t>(bits, bits + n));
  });
}

// Eagerly retire predicted-executed in-flight entries; `names` is a
// '\n'-joined list.  Writes up to `cap` finished seqs; returns count.
int64_t hvt_controller_finish_names(void* c, const char* names,
                                    int64_t len, uint64_t* out_seqs,
                                    int64_t cap) {
  std::vector<std::string> parts;
  const char* p = names;
  const char* end = names + len;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    if (nl == nullptr) nl = end;
    parts.emplace_back(p, nl - p);
    p = nl + 1;
  }
  std::vector<uint64_t> fin = Ctrl(c)->FinishNames(parts);
  int64_t n = static_cast<int64_t>(fin.size());
  for (int64_t i = 0; i < n && i < cap; ++i) out_seqs[i] = fin[i];
  return n;
}

// JSON stall report (parity: stall_inspector.cc warning text, but
// machine-readable): [{"name":..,"waiting_s":..,"present":[..],
// "missing":[..]}, ...]
int64_t hvt_controller_check_stalls(void* c, char* buf, int64_t cap) {
  return Staged(
      &Handle(c)->staged_stalls, reinterpret_cast<uint8_t*>(buf), cap, [c] {
        std::ostringstream ss;
        ss << '[';
        bool first = true;
        for (const StallEntry& se : Ctrl(c)->CheckStalls()) {
          if (!first) ss << ',';
          first = false;
          ss << "{\"name\":\"" << se.name
             << "\",\"waiting_s\":" << se.waiting_s << ",\"present\":[";
          for (size_t i = 0; i < se.present_ranks.size(); ++i) {
            if (i) ss << ',';
            ss << se.present_ranks[i];
          }
          ss << "],\"missing\":[";
          for (size_t i = 0; i < se.missing_ranks.size(); ++i) {
            if (i) ss << ',';
            ss << se.missing_ranks[i];
          }
          ss << "]}";
        }
        ss << ']';
        const std::string s = ss.str();
        return std::vector<uint8_t>(s.begin(), s.end());
      });
}

// ---- parallel memcpy (fusion staging; parity: thread_pool.cc use in
// MemcpyInFusionBuffer) --------------------------------------------------
void hvt_parallel_gather(uint8_t* dst, const uint8_t** srcs,
                         const int64_t* sizes, int64_t n) {
  std::vector<int64_t> offsets(n);
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = off;
    off += sizes[i];
  }
  GlobalPool().ParallelFor(n, [&](int64_t i) {
    memcpy(dst + offsets[i], srcs[i], sizes[i]);
  });
}

void hvt_parallel_scatter(const uint8_t* src, uint8_t** dsts,
                          const int64_t* sizes, int64_t n) {
  std::vector<int64_t> offsets(n);
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = off;
    off += sizes[i];
  }
  GlobalPool().ParallelFor(n, [&](int64_t i) {
    memcpy(dsts[i], src + offsets[i], sizes[i]);
  });
}

int hvt_pool_num_threads() { return GlobalPool().num_threads(); }

// ---- timeline ------------------------------------------------------------
void* hvt_timeline_new(const char* path, int rank) {
  TimelineWriter* t = new TimelineWriter(path, rank);
  if (!t->ok()) {
    delete t;
    return nullptr;
  }
  return t;
}

void hvt_timeline_free(void* t) { delete static_cast<TimelineWriter*>(t); }

void hvt_timeline_event(void* t, const char* name, char ph,
                        const char* category, double ts_us, double dur_us) {
  static_cast<TimelineWriter*>(t)->Event(name, ph, category, ts_us, dur_us);
}

void hvt_timeline_mark_cycle(void* t, double ts_us) {
  static_cast<TimelineWriter*>(t)->MarkCycle(ts_us);
}

void hvt_timeline_flush(void* t) { static_cast<TimelineWriter*>(t)->Flush(); }

}  // extern "C"
