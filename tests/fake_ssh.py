"""Local ssh transport shim for remote-path integration tests.

Accepts the ssh-shaped argv that ``build_ssh_command`` produces
(``[flags...] hostname command``) and executes the command with
``sh -c`` locally — so the launcher's REAL remote code path (env export
serialization, shell quoting, cwd handling, output piping, exit-code
propagation) runs end-to-end on a machine without sshd.  Selected via
``HVTPU_SSH_COMMAND="python tests/fake_ssh.py"``.
"""

import os
import sys


def main() -> int:
    args = sys.argv[1:]
    # skip ssh-style flags: -o/-p consume a value, bare -X flags don't
    i = 0
    while i < len(args) and args[i].startswith("-"):
        i += 2 if args[i] in ("-o", "-p", "-i", "-l") else 1
    if i >= len(args):
        print("fake_ssh: no hostname", file=sys.stderr)
        return 255
    hostname = args[i]
    command = " ".join(args[i + 1:])
    if not command:
        print("fake_ssh: no command", file=sys.stderr)
        return 255
    # visible marker so tests can assert this transport actually ran
    print(f"FAKE_SSH host={hostname}", file=sys.stderr, flush=True)
    os.execvp("sh", ["sh", "-c", command])
    return 255  # pragma: no cover - execvp does not return


if __name__ == "__main__":
    sys.exit(main())
