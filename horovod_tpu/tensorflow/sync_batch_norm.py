"""Cross-rank synchronized BatchNormalization for tf.keras (parity:
``horovod/tensorflow/sync_batch_norm.py`` ``SyncBatchNormalization``).

The reference subclasses the keras BatchNormalization layer and
replaces its batch-moment computation with a cross-rank one
(``_calculate_mean_and_var`` in tf.keras 2); Keras 3 exposes the same
seam as ``_moments``.  Training-mode statistics are computed over the
GLOBAL batch: local (sum, sum-of-squares, count) ride ONE fused
allreduce — the same wire structure as the torch frontend's
``SyncBatchNorm`` — and the backward differentiates through the
allreduce via the registered collective gradients, so the gradient
sums match cross-rank batchnorm semantics without a hand-written
adjoint.  Moving-average updates and inference mode are inherited
unchanged from the base layer.
"""

from __future__ import annotations

import keras
import tensorflow as tf

from . import mpi_ops


class SyncBatchNormalization(keras.layers.BatchNormalization):
    """Drop-in for ``keras.layers.BatchNormalization`` whose training
    statistics span every rank's batch (parity:
    hvd.SyncBatchNormalization; ``process_set`` scopes the stats to a
    subset of ranks)."""

    def __init__(self, *args, process_set=None, **kwargs):
        # the cross-rank hook lives on the Keras 3 `_moments` seam; a
        # base class without it (keras 2's layer uses
        # _calculate_mean_and_var) would silently train on LOCAL stats
        if not hasattr(keras.layers.BatchNormalization, "_moments"):
            raise RuntimeError(
                "SyncBatchNormalization requires Keras 3 "
                "(keras.layers.BatchNormalization._moments seam not "
                "found)")
        super().__init__(*args, **kwargs)
        # a ProcessSet object, or its id (what get_config round-trips
        # — the engine resolves ids against the live table)
        self._process_set = process_set

    def get_config(self):
        config = super().get_config()
        ps = self._process_set
        if ps is not None and not isinstance(ps, int):
            if ps.process_set_id is None:
                # an unbound set would serialize as None and silently
                # widen the reloaded layer to the GLOBAL set
                raise ValueError(
                    "SyncBatchNormalization's process_set is not "
                    "registered — call hvd.add_process_set(ps) (after "
                    "init) before serializing the model")
            ps = ps.process_set_id
        config["process_set"] = ps
        return config

    def _moments(self, inputs, mask):
        import horovod_tpu as _hvt
        from ..core.process_set import participant_count

        if not _hvt.is_initialized() \
                or participant_count(self._process_set) == 1:
            # single rank: base-layer semantics
            return super()._moments(inputs, mask)
        if mask is not None:
            # falling back to local masked moments would silently
            # desync the ranks' statistics — refuse loudly
            raise NotImplementedError(
                "SyncBatchNormalization does not support masked "
                "moments in multi-rank training")

        x = tf.cast(inputs, tf.float32)
        axes = list(self._reduction_axes)
        local_sum = tf.reduce_sum(x, axis=axes)
        local_sqsum = tf.reduce_sum(tf.square(x), axis=axes)
        # per-rank row counts may differ (ragged final batches): the
        # count rides the same fused collective as the sums
        local_count = tf.cast(
            tf.size(x) / tf.size(local_sum), tf.float32)
        c = tf.size(local_sum)
        packed = tf.concat(
            [local_sum, local_sqsum, tf.reshape(local_count, [1])], 0)
        packed = mpi_ops.allreduce(
            packed, op=mpi_ops.Sum, name="sync_bn.stats",
            process_set=self._process_set)
        g_sum = packed[:c]
        g_sqsum = packed[c:2 * c]
        # Every rank can legitimately see an empty batch on the same
        # step (ragged tail of a small dataset): g_count == 0 would
        # turn mean/variance into NaN and permanently poison the
        # moving statistics.  Clamping degrades the step to zero
        # moments instead (sums are zero too), matching the base
        # layer's no-op behavior on empty input.
        g_count = tf.maximum(packed[2 * c], 1.0)
        mean = g_sum / g_count
        # E[x^2]-E[x]^2 can go fractionally negative via float32
        # cancellation when |mean| >> std — rsqrt(var+eps) would then
        # poison the moving stats with NaN
        variance = tf.maximum(
            g_sqsum / g_count - tf.square(mean), 0.0)
        return (tf.cast(mean, inputs.dtype),
                tf.cast(variance, inputs.dtype))
