"""Minimal fixture twin of native/wire.py (wire-twin clean case)."""

REQUEST_MAGIC = 0x52545648
RESPONSE_MAGIC = 0x50545648
WIRE_VERSION = 3

ALLREDUCE, BARRIER = range(2)
RED_SUM, RED_AVERAGE = range(2)
DTYPE_IDS = {"uint8": 0, "float32": 1}
DTYPE_SIZES = {0: 1, 1: 4}


class Entry:
    def signature(self):
        dims = "".join(f"{d}," for d in self.shape)
        return f"{self.name}|{self.dtype}|{dims}"


def _write_entry(w, e):
    w.u64(e.seq)
    w.s(e.name)
    w.u8(e.dtype)


def serialize_request_list(rl):
    w = _W()
    w.u32(REQUEST_MAGIC)
    w.u32(WIRE_VERSION)
    w.i32(rl.rank)
    # delimiter drift seed: both twins agree (so the generic order
    # check stays quiet) but the burst u32 pair sits BEFORE the flag
    # bytes — only the absolute-position check may catch this.
    w.u32(rl.burst_id)
    w.u32(rl.burst_len)
    w.u8(1 if rl.joined else 0)
    w.u8(1 if rl.shutdown else 0)
    w.u8(1 if rl.cache_bypass else 0)
    for rq in rl.requests:
        _write_entry(w, rq.entry)
    return w.bytes()


def serialize_response_list(rl):
    w = _W()
    w.u32(RESPONSE_MAGIC)
    w.u32(WIRE_VERSION)
    w.u8(1 if rl.shutdown else 0)
    w.s(rl.error)
    return w.bytes()
