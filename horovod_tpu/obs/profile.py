"""Device-trace op profiling: capture + summarize XLA op time.

Parity surface: the reference's per-op visibility comes from the
Timeline (``horovod/common/timeline.cc`` — tensor lifecycle phases) and
NVTX ranges for Nsight (``nvtx_op_range.cc``).  On TPU the equivalent
ground truth is the XLA device trace ``jax.profiler`` writes; this
module adds the missing half: turning the captured ``*.xplane.pb``
into a per-op-kind time table WITHOUT TensorBoard (the usual
``tensorboard_plugin_profile`` stack is protobuf-version-fragile and
absent from many images).

The xplane file is parsed with a minimal wire-format reader (~60
lines): XSpace -> planes -> lines -> events plus the event-metadata
table.  Only length-delimited fields and varints are needed.

Typical use (exactly the loop used to find that the ResNet-50 step is
34% BatchNorm column-reduces)::

    from horovod_tpu.obs import profile
    with profile.trace("/tmp/prof"):
        step(...); jax.block_until_ready(...)
    for row in profile.op_summary("/tmp/prof")[:10]:
        print(row)
"""

from __future__ import annotations

import contextlib
import glob
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

# --- minimal protobuf wire reader ------------------------------------


class TruncatedProfile(ValueError):
    """An xplane file ended mid-message (partial profiler flush, e.g.
    the process died while jax.profiler was still writing).  Raised
    with a position so callers can report how far the parse got;
    :func:`load_profile` converts it into a ``status="truncated"``
    result instead of propagating."""


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    n = len(buf)
    while True:
        if pos >= n:
            raise TruncatedProfile(f"varint ran off buffer at byte {pos}")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise TruncatedProfile(f"varint wider than 64 bits at {pos}")


def _fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    """Yield (field_number, wire_type, payload) over a message buffer.
    Varints yield their value encoded back as int in payload position;
    64/32-bit fields yield raw bytes."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:          # varint
            val, pos = _read_varint(buf, pos)
            yield field, wt, val
        elif wt == 2:        # length-delimited
            ln, pos = _read_varint(buf, pos)
            if pos + ln > n:
                raise TruncatedProfile(
                    f"length-delimited field {field} ({ln} bytes) runs "
                    f"off buffer at byte {pos}")
            yield field, wt, buf[pos:pos + ln]
            pos += ln
        elif wt == 1:        # 64-bit
            if pos + 8 > n:
                raise TruncatedProfile(f"64-bit field truncated at {pos}")
            yield field, wt, buf[pos:pos + 8]
            pos += 8
        elif wt == 5:        # 32-bit
            if pos + 4 > n:
                raise TruncatedProfile(f"32-bit field truncated at {pos}")
            yield field, wt, buf[pos:pos + 4]
            pos += 4
        else:  # pragma: no cover - groups unused by xplane
            raise ValueError(f"unsupported wire type {wt}")


# xplane.proto field numbers (tsl/profiler/protobuf/xplane.proto):
# XSpace.planes=1; XPlane.name=2, .lines=3, .event_metadata=4 (map;
# field 5 is the STAT metadata map — do not confuse the two);
# XLine.events=4, .name=2, .timestamp_ns=3;
# XEvent.metadata_id=1, .offset_ps=2, .duration_ps=3;
# XEventMetadata.id=1, .name=2, .display_name=4.


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    mid, name, display = 0, "", ""
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            mid = v
        elif f == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif f == 4 and wt == 2:
            display = v.decode("utf-8", "replace")
    return mid, (display or name)


def _parse_plane(buf: bytes):
    name = ""
    lines: List[bytes] = []
    emeta: Dict[int, str] = {}
    for f, wt, v in _fields(buf):
        if f == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif f == 3 and wt == 2:
            lines.append(v)
        elif f == 4 and wt == 2:
            # map<int64, XEventMetadata> entry: key=1, value=2
            meta_buf = None
            for mf, mwt, mv in _fields(v):
                if mf == 2 and mwt == 2:
                    meta_buf = mv
            if meta_buf is not None:
                mid, mname = _parse_event_metadata(meta_buf)
                emeta[mid] = mname
    return name, lines, emeta


def _parse_line(buf: bytes):
    name = ""
    ts_ns = 0
    events: List[bytes] = []
    for f, wt, v in _fields(buf):
        if f == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif f == 3 and wt == 0:
            ts_ns = v
        elif f == 4 and wt == 2:
            events.append(v)
    return name, ts_ns, events


def _parse_event(buf: bytes) -> Tuple[int, int, int]:
    mid, off_ps, dur_ps = 0, 0, 0
    for f, wt, v in _fields(buf):
        if f == 1 and wt == 0:
            mid = v
        elif f == 2 and wt == 0:
            off_ps = v
        elif f == 3 and wt == 0:
            dur_ps = v
    return mid, off_ps, dur_ps


# --- public API --------------------------------------------------------


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a device trace (thin wrapper over jax.profiler.trace so
    callers need only this module)."""
    import jax

    with jax.profiler.trace(logdir):
        yield


def _find_xplanes(logdir: str) -> List[str]:
    return sorted(
        glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                  recursive=True)
    )


def plane_names(logdir: str) -> List[str]:
    """Names of all planes in the newest trace (debugging aid)."""
    paths = _find_xplanes(logdir)
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    with open(paths[-1], "rb") as f:
        space = f.read()
    return [
        _parse_plane(v)[0]
        for f_no, wt, v in _fields(space)
        if f_no == 1 and wt == 2
    ]


def op_summary(logdir: str, *, plane_substr: str = "/device:",
               line_name: str = "XLA Ops",
               group: bool = True) -> List[dict]:
    """Aggregate per-op durations from the newest trace under logdir.

    Returns rows ``{"op": kind, "total_ms": t, "count": c}`` sorted by
    time, descending.  ``group=True`` buckets ops by fusion kind
    (multiply_reduce_fusion.123 -> multiply_reduce_fusion), which is
    the actionable granularity (e.g. BN stats reduces vs convs).
    ``plane_substr`` selects planes — the default matches the TPU/GPU
    device planes; CPU-only traces have host planes only (pass
    ``plane_substr="/host:CPU"`` with an appropriate ``line_name``).
    """
    paths = _find_xplanes(logdir)
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    with open(paths[-1], "rb") as f:
        space = f.read()

    agg: Dict[str, List[float]] = {}
    for f_no, wt, plane_buf in _fields(space):
        if f_no != 1 or wt != 2:
            continue
        pname, lines, emeta = _parse_plane(plane_buf)
        if plane_substr not in pname:
            continue
        for line_buf in lines:
            lname, _ts_ns, events = _parse_line(line_buf)
            if lname != line_name:
                continue
            for ev in events:
                mid, _off_ps, dur_ps = _parse_event(ev)
                name = emeta.get(mid, f"op{mid}")
                if name.startswith("while"):
                    continue  # container; children are separate events
                if group:
                    name = re.sub(r"[.\d]+$", "", name.split(" = ")[0]
                                  .lstrip("%"))
                cell = agg.setdefault(name, [0.0, 0])
                cell[0] += dur_ps / 1e9  # ps -> ms
                cell[1] += 1
    rows = [
        {"op": k, "total_ms": round(v[0], 3), "count": v[1]}
        for k, v in agg.items()
    ]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def device_time_ms(logdir: str, **kw) -> float:
    """Total device busy time in the trace (sum over op rows)."""
    return round(sum(r["total_ms"] for r in op_summary(logdir, **kw)), 3)


# --- timestamped intervals for the overlap join (obs/stepprof.py) -----

# HLO op names that are wire collectives.  XLA spells them
# all-reduce/all-gather/... (plus -start/-done pairs for async and
# fusion.NNN wrappers whose display name keeps the root op); hvtpu's
# Pallas ring kernels surface as collective-permute chains.
_COMM_OP_RE = re.compile(
    r"(all[-_]?reduce|all[-_]?gather|all[-_]?to[-_]?all|"
    r"reduce[-_]?scatter|collective[-_]?permute|"
    r"(^|[^a-z])(send|recv)([^a-z]|$))",
    re.IGNORECASE)


def is_comm_op(name: str) -> bool:
    """True when an XLA op name denotes a communication op."""
    return bool(_COMM_OP_RE.search(name))


def load_profile(logdir: str, *, plane_substr: str = "/device:",
                 line_name: str = "XLA Ops") -> dict:
    """Timestamped device op intervals from the newest trace — the
    overlap profiler's device-truth input.  NEVER raises: CPU-only CI
    (no xplane written), an empty capture, or a truncated flush all
    come back as an explicit status so callers degrade to host-only
    attribution instead of crashing mid-varint.

    Returns::

        {"status": "ok" | "no-profile" | "empty" | "truncated",
         "reason": str,          # human-readable when status != ok
         "path":   str | None,   # xplane file parsed (newest)
         "planes": {plane_name: [interval, ...]}}

    where each interval is ``{"op", "t0_us", "t1_us", "comm"}`` with
    timestamps in the profiler's wall clock (XLine.timestamp_ns +
    XEvent.offset_ps), and ``comm`` flags wire-collective ops.  Only
    planes matching ``plane_substr`` and lines named ``line_name`` are
    scanned (the device op line); a ``status="ok"`` result can still
    carry zero planes when the capture saw no matching device plane —
    callers should treat that the same as "empty".
    """
    paths = _find_xplanes(logdir)
    if not paths:
        return {"status": "no-profile", "path": None, "planes": {},
                "reason": f"no *.xplane.pb under {logdir}"}
    path = paths[-1]
    try:
        with open(path, "rb") as f:
            space = f.read()
    except OSError as e:
        return {"status": "no-profile", "path": path, "planes": {},
                "reason": f"unreadable xplane: {e}"}
    if not space:
        return {"status": "empty", "path": path, "planes": {},
                "reason": "zero-byte xplane file"}
    planes: Dict[str, List[dict]] = {}
    try:
        for f_no, wt, plane_buf in _fields(space):
            if f_no != 1 or wt != 2:
                continue
            pname, lines, emeta = _parse_plane(plane_buf)
            if plane_substr not in pname:
                continue
            ivs = planes.setdefault(pname, [])
            for line_buf in lines:
                lname, ts_ns, events = _parse_line(line_buf)
                if lname != line_name:
                    continue
                base_us = ts_ns / 1e3
                for ev in events:
                    mid, off_ps, dur_ps = _parse_event(ev)
                    name = emeta.get(mid, f"op{mid}")
                    if name.startswith("while"):
                        continue  # container; children are separate
                    t0 = base_us + off_ps / 1e6
                    ivs.append({
                        "op": name,
                        "t0_us": t0,
                        "t1_us": t0 + dur_ps / 1e6,
                        "comm": is_comm_op(name),
                    })
    except (TruncatedProfile, ValueError) as e:
        return {"status": "truncated", "path": path, "planes": {},
                "reason": str(e)}
    for ivs in planes.values():
        ivs.sort(key=lambda r: r["t0_us"])
    if not any(planes.values()):
        return {"status": "empty", "path": path, "planes": planes,
                "reason": (f"no events on line {line_name!r} of planes "
                           f"matching {plane_substr!r} (CPU-only "
                           "capture?)")}
    return {"status": "ok", "path": path, "planes": planes, "reason": ""}
