"""VGG in flax.linen, bf16-first.

The reference's README benchmark trio is Inception V3 / ResNet-101 /
VGG-16 (``docs/benchmarks.rst``; VGG-16 is its comm-bound case — ~68%
scaling at 128 GPUs because of the 138M-parameter dense head), so the
same architectures are available here for like-for-like scaling runs.

TPU notes: bf16 compute, fp32 params and logits; NHWC convs; the
classifier keeps the reference's 4096-wide FC stack — exactly the
gradient payload that makes VGG the fusion/compression stress test.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Each entry: number of 3x3 convs in the stage, then a 2x2/2 maxpool.
_CFG = {
    11: (1, 1, 2, 2, 2),
    13: (2, 2, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}
_WIDTHS = (64, 128, 256, 512, 512)


class VGG(nn.Module):
    depth: int = 16
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train  # no batch norm / dropout state in the classic net
        x = x.astype(self.dtype)
        for stage, (reps, width) in enumerate(
                zip(_CFG[self.depth], _WIDTHS)):
            for i in range(reps):
                x = nn.Conv(width, (3, 3), padding="SAME",
                            dtype=self.dtype,
                            name=f"conv{stage}_{i}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        n = x.shape[0]
        x = x.reshape(n, -1)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


VGG16 = partial(VGG, depth=16)
VGG19 = partial(VGG, depth=19)
