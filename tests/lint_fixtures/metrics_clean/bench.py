"""bench fixture (clean): the metric contract the bench guard enforces."""

REQUIRED_METRIC_KEYS = [
    "hvtpu_fixture_steps_total",
    "hvtpu_fixture_exposed_seconds",
    "hvtpu_fixture_overlap_fraction",
]
