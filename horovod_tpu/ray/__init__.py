"""Ray integration surface: actors when Ray is live, local fallback.

Parity surface: ``horovod.ray.RayExecutor`` (horovod/ray/runner.py) —
``start()`` / ``run(fn)`` / ``run_remote``+``execute`` / ``shutdown``
driving one Horovod rank per Ray worker.

Two execution paths, chosen at ``start()``:

- **Ray actors** (parity: BaseHorovodWorker actors): when ``ray`` is
  importable AND ``ray.is_initialized()``, each rank runs inside a Ray
  actor (``num_cpus=cpus_per_worker``, ``num_gpus`` when asked).  The
  driver gathers each actor's node IP, derives the rank/local/cross
  topology by host exactly like the hvtpurun launcher does by
  hostfile, points every rank at rank 0's node for the JAX
  coordination service, and runs ``fn`` on all actors.  Placement
  GROUPS stay out of scope (SURVEY.md §7.3) — actors are scheduled by
  Ray's default scheduler.
- **local mode** otherwise: ranks as local worker processes through
  the hvtpurun machinery — the reference's own CI exercises
  RayExecutor on a local Ray cluster the same way.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("horovod_tpu")


def _probe_ray():
    """The live-Ray probe: a user with a real Ray cluster must get
    actors, not silently subprocesses on the driver node."""
    try:
        import ray
    except Exception:
        return None
    try:
        if not ray.is_initialized():
            return None
    except Exception:
        return None
    return ray


class _ActorWorker:
    """One rank inside a Ray actor process (parity:
    horovod/ray/runner.py BaseHorovodWorker)."""

    def node_info(self):
        """(node ip, a free port) — rank 0's answer seeds the JAX
        coordination-service address for every rank."""
        import socket

        from ..runner.launch import find_free_port

        try:
            from ray.util import get_node_ip_address

            ip = get_node_ip_address()
        except Exception:
            ip = socket.gethostbyname(socket.gethostname())
        return ip, find_free_port(bind_addr="")

    def setup(self, env: Dict[str, str]):
        import os

        os.environ.update(env)
        return True

    def execute(self, fn, args=(), kwargs=None):
        return fn(*args, **(kwargs or {}))


def _topology_envs(infos, env_vars=None,
                   cpu_devices=None) -> List[Dict[str, str]]:
    """Per-rank launcher-equivalent env from the actors' node IPs:
    rank/size, local rank/size by host, cross rank/size per
    LOCAL RANK across hosts (exactly hosts.py get_host_assignments'
    derivation — the cross communicator is the set of hosts holding a
    slot at this local rank), and the coordination-service address on
    rank 0's node."""
    size = len(infos)
    ip0, port0 = infos[0]
    hosts: List[str] = []
    for ip, _p in infos:
        if ip not in hosts:
            hosts.append(ip)
    by_host = {h: [r for r, (ip, _p) in enumerate(infos) if ip == h]
               for h in hosts}
    local_sizes = {len(v) for v in by_host.values()}
    uniform = local_sizes.pop() if len(local_sizes) == 1 else 0
    # cross layout per local_rank (mirrors runner/hosts.py): with a
    # ragged 2+1 placement, local_rank 1 exists on one host only, so
    # its cross communicator has size 1 — not the host count
    by_local_rank: Dict[int, List[str]] = {}
    for h in hosts:
        for lr in range(len(by_host[h])):
            by_local_rank.setdefault(lr, []).append(h)
    envs = []
    for rank, (ip, _p) in enumerate(infos):
        locals_ = by_host[ip]
        lr = locals_.index(rank)
        cross_hosts = by_local_rank[lr]
        env = {
            "HVTPU_RANK": str(rank),
            "HVTPU_SIZE": str(size),
            "HVTPU_LOCAL_RANK": str(lr),
            "HVTPU_LOCAL_SIZE": str(len(locals_)),
            "HVTPU_CROSS_RANK": str(cross_hosts.index(ip)),
            "HVTPU_CROSS_SIZE": str(len(cross_hosts)),
            "HVTPU_UNIFORM_LOCAL_SIZE": str(uniform),
            "HVTPU_COORDINATOR_ADDR": ip0,
            "HVTPU_COORDINATOR_PORT": str(port0),
        }
        if cpu_devices is not None:
            # same CPU-platform forcing the local launcher path
            # applies (actors must not silently target a different
            # backend than the fallback would)
            env["HVTPU_CPU_DEVICES"] = str(cpu_devices)
        env.update(env_vars or {})
        envs.append(env)
    return envs


class RayExecutor:
    """Executor with the reference's lifecycle shape: Ray actors when
    a Ray cluster is live, local worker processes otherwise.

    >>> ex = RayExecutor(num_workers=2)
    >>> ex.start()
    >>> results = ex.run(train_fn, args=(cfg,))
    >>> ex.shutdown()
    """

    def __init__(self, settings=None, *, num_workers: Optional[int] = None,
                 num_hosts: Optional[int] = None,
                 num_workers_per_host: Optional[int] = None,
                 cpu_devices: Optional[int] = 1,
                 env_vars: Optional[Dict[str, str]] = None,
                 use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: Optional[int] = None):
        # reference world-size arithmetic: either num_workers directly
        # or num_hosts x num_workers_per_host — silently running a
        # different world size than asked would corrupt training
        if num_workers is None and num_hosts is not None:
            num_workers = num_hosts * (num_workers_per_host or 1)
        elif (num_workers is not None and num_hosts is not None
              and num_workers != num_hosts * (num_workers_per_host or 1)):
            raise ValueError(
                "specify num_workers OR num_hosts*num_workers_per_host, "
                "not conflicting values of both"
            )
        self.num_workers = num_workers or 2
        self.cpu_devices = cpu_devices
        self.env_vars = env_vars
        self.use_gpu = use_gpu
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker
        self._started = False
        self._ray = None
        self._actors: Optional[list] = None

    def start(self):
        """Probe for a live Ray cluster; create one actor per rank
        when found (parity: RayExecutor.start creating
        BaseHorovodWorker actors), else arm the local fallback."""
        self._ray = _probe_ray()
        if self._ray is not None:
            self._start_actors()
        else:
            logger.info(
                "RayExecutor: no initialized Ray cluster found; "
                "running ranks as local worker processes")
        self._started = True

    def _start_actors(self):
        ray = self._ray
        opts: Dict[str, Any] = {"num_cpus": self.cpus_per_worker}
        if self.use_gpu:
            opts["num_gpus"] = self.gpus_per_worker or 1
        actor_cls = ray.remote(**opts)(_ActorWorker)
        self._actors = [actor_cls.remote()
                        for _ in range(self.num_workers)]
        infos = ray.get(
            [a.node_info.remote() for a in self._actors])
        # CPU-platform forcing must never override reserved
        # accelerators: use_gpu actors keep their native platform
        envs = _topology_envs(
            infos, self.env_vars,
            None if self.use_gpu else self.cpu_devices)
        ray.get([a.setup.remote(e)
                 for a, e in zip(self._actors, envs)])

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict[str, Any]] = None) -> List[Any]:
        """Run ``fn`` on every rank, return per-rank results ordered by
        rank (parity: RayExecutor.run)."""
        if not self._started:
            raise RuntimeError("RayExecutor.start() must be called first")
        if self._actors is not None:
            return self._ray.get(
                self.run_remote(fn, args=args, kwargs=kwargs))
        from .. import runner

        return runner.run(
            fn, args=args, kwargs=kwargs, np=self.num_workers,
            # same platform rule as the actor path: use_gpu must not
            # be silently downgraded to the CPU platform
            cpu_devices=None if self.use_gpu else self.cpu_devices,
            env=self.env_vars,
        )

    # reference API aliases
    def run_remote(self, fn: Callable, args: tuple = (),
                   kwargs: Optional[Dict[str, Any]] = None):
        """Actor mode returns Ray ObjectRefs to pass to ``execute``
        (reference contract); local mode executes eagerly and returns
        the results list."""
        if self._actors is not None:
            return [a.execute.remote(fn, args, kwargs)
                    for a in self._actors]
        return self.run(fn, args=args, kwargs=kwargs)

    def execute(self, fn_or_results):
        """Reference shape: ``execute(fn)`` runs fn on every worker.
        Also accepts the output of :meth:`run_remote` (ObjectRefs in
        actor mode, a results list in local mode)."""
        if callable(fn_or_results):
            return self.run(fn_or_results)
        if self._actors is not None and isinstance(fn_or_results, list):
            return self._ray.get(fn_or_results)
        return fn_or_results

    def shutdown(self):
        if self._actors is not None:
            for a in self._actors:
                try:
                    self._ray.kill(a)
                except Exception:
                    pass
            self._actors = None
        self._ray = None
        self._started = False


class ElasticRayExecutor:
    """Elastic executor with the reference's lifecycle shape (parity:
    ``horovod.ray.ElasticRayExecutor``): ``start()`` then ``run(fn)``
    where ``fn`` follows the elastic contract (``hvd.elastic.State`` +
    ``@hvd.elastic.run``).  Local-mode: ranks are launched under the
    elastic DRIVER (restart-based reconfiguration, durable commits),
    not Ray actors — placement-group scheduling stays out of scope
    (SURVEY.md §7.3).  A ``host_discovery_script`` makes the world
    resize live, the reference's Ray-autoscaler discovery analog."""

    def __init__(self, settings=None, *,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 num_workers: Optional[int] = None,
                 cpu_devices: Optional[int] = 1,
                 env_vars: Optional[Dict[str, str]] = None,
                 host_discovery_script: Optional[str] = None,
                 override_discovery: bool = True,  # source compat
                 use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: Optional[int] = None):
        # reference source compat: ElasticRayExecutor carries its
        # elastic bounds in a settings object (create_settings(min_np,
        # max_np, ...)); honor those rather than silently dropping them
        if settings is not None:
            min_workers = min_workers or getattr(
                settings, "min_np", None) or getattr(
                settings, "min_workers", None)
            max_workers = max_workers or getattr(
                settings, "max_np", None) or getattr(
                settings, "max_workers", None)
            host_discovery_script = host_discovery_script or getattr(
                settings, "discovery_script", None)
        self.num_workers = num_workers or max_workers or min_workers or 2
        self.min_workers = min_workers
        self.max_workers = max_workers
        if min_workers is not None and min_workers > self.num_workers:
            raise ValueError(
                f"min_workers={min_workers} exceeds the world size "
                f"{self.num_workers} (num_workers/max_workers): the "
                "static local discovery could never satisfy it")
        self.cpu_devices = cpu_devices
        self.env_vars = env_vars
        self.host_discovery_script = host_discovery_script
        self._started = False

    @staticmethod
    def create_settings(min_np: Optional[int] = None,
                        max_np: Optional[int] = None,
                        discovery_script: Optional[str] = None,
                        **_ignored):
        """Reference-shaped settings factory (parity:
        ElasticRayExecutor.create_settings): a plain namespace the
        constructor reads its elastic bounds from."""
        from types import SimpleNamespace

        return SimpleNamespace(min_np=min_np, max_np=max_np,
                               discovery_script=discovery_script)

    def start(self):
        self._started = True

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[Dict[str, Any]] = None) -> List[Any]:
        """Run ``fn`` under the elastic driver; per-rank results of the
        final world, ordered by rank."""
        if not self._started:
            raise RuntimeError(
                "ElasticRayExecutor.start() must be called first")
        from .. import runner

        return runner.run_elastic(
            fn, args=args, kwargs=kwargs,
            num_proc=self.num_workers,
            min_np=self.min_workers, max_np=self.max_workers,
            cpu_devices=self.cpu_devices, env=self.env_vars,
            host_discovery_script=self.host_discovery_script,
        )

    def shutdown(self):
        self._started = False
