"""tf.keras MNIST example — the horovod_tpu port surface of the
reference's examples/tensorflow2/tensorflow2_keras_mnist.py: only the
import line changes (``import horovod.tensorflow.keras as hvd`` ->
``import horovod_tpu.keras as hvd``).  Synthetic MNIST-shaped data
keeps it hermetic.

Run:  hvtpurun -np 2 --cpu-devices 1 python examples/tensorflow2_keras_mnist.py
"""

import argparse

import numpy as np
import keras

import horovod_tpu.keras as hvd


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--train-size", type=int, default=2048)
    args = p.parse_args()

    hvd.init()

    rng = np.random.RandomState(0)
    x = rng.rand(args.train_size, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = (x @ w).argmax(axis=1)

    # shard by rank (DistributedSampler analog)
    n = len(x) // hvd.size()
    lo = hvd.rank() * n
    x, y = x[lo:lo + n], y[lo:lo + n]

    keras.utils.set_random_seed(42 + hvd.rank())
    model = keras.Sequential([
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10, activation="softmax"),
    ])

    # Horovod idiom: scale LR by size, wrap optimizer, broadcast at start.
    opt = keras.optimizers.SGD(learning_rate=args.lr * hvd.size())
    opt = hvd.DistributedOptimizer(opt)
    model.compile(
        optimizer=opt, loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    hist = model.fit(
        x, y, epochs=args.epochs, batch_size=args.batch_size,
        verbose=1 if hvd.rank() == 0 else 0,
        callbacks=[
            hvd.callbacks.BroadcastGlobalVariablesCallback(0),
            hvd.callbacks.MetricAverageCallback(),
            hvd.callbacks.LearningRateWarmupCallback(
                warmup_epochs=1, initial_lr=args.lr
            ),
        ],
    )

    # ranks must stay in lockstep
    csum = float(sum(np.sum(w) for w in model.get_weights()))
    sums = hvd.allgather_object(csum)
    assert all(abs(s - sums[0]) < 1e-5 for s in sums), sums
    if hvd.rank() == 0:
        print(
            f"final loss {hist.history['loss'][-1]:.4f}; "
            f"ranks consistent ({hvd.size()} ranks)",
            flush=True,
        )


if __name__ == "__main__":
    main()
