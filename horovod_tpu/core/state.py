"""Process-wide global state, the analog of the reference's
``HorovodGlobalState`` (horovod/common/operations.cc) plus the init /
shutdown choreography of ``InitializeHorovodOnce`` / ``horovod_init``.

Key design departure (SURVEY.md §7.0): on the jitted SPMD path there is
no background controller thread — program order *is* the coordination.
``init()`` therefore only (1) joins the JAX coordination service when a
multi-process launch is detected (replacing the MPI/Gloo rendezvous),
(2) snapshots config from env, and (3) builds the topology / process-set
table.  The eager mini-controller (horovod_tpu.eager) is started lazily
on first use of the async eager API.
"""

from __future__ import annotations

import atexit
import threading
import time
from typing import Optional

import jax

from .config import Config
from .exceptions import NotInitializedError
from .process_set import ProcessSet, ProcessSetTable
from .topology import Topology


class GlobalState:
    def __init__(self):
        self.initialized = False
        self.config: Optional[Config] = None
        self.topology: Optional[Topology] = None
        self.process_set_table: Optional[ProcessSetTable] = None
        self.rank = 0
        self.size = 1
        self.local_rank = 0
        self.local_size = 1
        self.cross_rank = 0
        self.cross_size = 1
        self.distributed_initialized_by_us = False
        # Lazily-started eager mini-controller (horovod_tpu.eager).
        self.controller = None
        # Sync-path stall inspector (comm/stall.py), created lazily on
        # the first guarded eager collective; False = probed, no client.
        self.sync_stall = None
        # Monotonic per-process init counter: namespaces the stall KV
        # marks so a shutdown→init cycle (elastic in-process resync)
        # can never read the previous session's stale marks.
        self.init_generation = 0
        # Timeline writer (horovod_tpu.obs.timeline), if enabled.
        self.timeline = None
        # Autotuner (horovod_tpu.obs.autotune), if enabled.
        self.autotuner = None
        # Fleet health publisher (fleet/health.py), rank 0 only when
        # HVTPU_FLEET_JOB names the owning fleet job.
        self.health_reporter = None


_state = GlobalState()
_init_lock = threading.Lock()


def _job_debug_state() -> dict:
    """Job identity for the metrics server's /debug endpoint
    (registered by init(), removed by shutdown())."""
    import os as _os

    return {
        "initialized": _state.initialized,
        "rank": _state.rank,
        "size": _state.size,
        "local_rank": _state.local_rank,
        "local_size": _state.local_size,
        "init_generation": _state.init_generation,
        "elastic_generation": int(
            _os.environ.get("HVTPU_ELASTIC_GENERATION", "0") or 0),
    }


def _replay_journal(kv, rank: int) -> None:
    """Relaunched incarnation: re-publish this rank's journaled durable
    keys (restore-quorum votes, drain accounting — core/journal.py)
    into the fresh coordination KV.  Every elastic relaunch starts an
    EMPTY KV (new coordinator port, possibly a re-elected coordinator
    host), so without replay a coordinator loss also loses the
    accounting the recovery protocols need.  Best-effort: a failed
    replay degrades to the protocols recomputing from scratch."""
    import logging as _logging
    import os as _os

    if kv is None:
        return
    if int(_os.environ.get("HVTPU_ELASTIC_GENERATION", "0") or 0) <= 0:
        return
    try:
        from .journal import default_journal

        journal = default_journal(rank)
        if journal is None or len(journal) == 0:
            return
        replayed = journal.replay(kv)
        from ..obs import flight as _flight

        _flight.note("journal_replayed", rank=rank, keys=replayed,
                     journaled=len(journal))
        _logging.getLogger("horovod_tpu").info(
            "kv journal: rank %d replayed %d of %d durable key(s) "
            "into the fresh coordinator", rank, replayed, len(journal))
    except Exception:
        _logging.getLogger("horovod_tpu").warning(
            "kv journal: replay failed (protocols will recompute)",
            exc_info=True)


def _coordination_client_active() -> bool:
    """True if jax.distributed is already initialized, checked WITHOUT
    triggering XLA backend initialization (jax.process_count() would)."""
    try:
        from jax._src import distributed as _jd

        return _jd.global_state.client is not None
    except Exception:
        return False


def force_cpu_devices(n: int):
    """Request the CPU platform with ``n`` XLA devices, portably across
    jax versions.  Must run before anything initializes the XLA backend
    (a ``jax.devices()`` call locks the platform in)."""
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # Older jax (< 0.5) spells this as an XLA flag; it is read at
        # backend init, which hasn't happened yet here.
        import os as _os

        flag = f"--xla_force_host_platform_device_count={n}"
        if flag not in _os.environ.get("XLA_FLAGS", ""):
            _os.environ["XLA_FLAGS"] = (
                _os.environ.get("XLA_FLAGS", "") + " " + flag).strip()


def global_state() -> GlobalState:
    return _state


def initialized() -> bool:
    return _state.initialized


def require_init(name: str = "this operation") -> GlobalState:
    if not _state.initialized:
        raise NotInitializedError(name)
    return _state


def init(config: Optional[Config] = None) -> GlobalState:
    """Initialize horovod_tpu (idempotent, like ``InitializeHorovodOnce``)."""
    with _init_lock:
        if _state.initialized:
            return _state
        cfg = config or Config.from_env()

        # Apply the configured log level to the framework's logger tree
        # (parity: HOROVOD_LOG_LEVEL gating logging.cc's LOG macros).
        import logging as _logging

        _LEVELS = {
            "trace": _logging.DEBUG, "debug": _logging.DEBUG,
            "info": _logging.INFO, "warning": _logging.WARNING,
            "error": _logging.ERROR, "fatal": _logging.CRITICAL,
        }
        _logger = _logging.getLogger("horovod_tpu")
        _level = _LEVELS.get(str(cfg.log_level).lower(), _logging.WARNING)
        _logger.setLevel(_level)

        # Elastic worker: install the driver-notification (SIGUSR1)
        # handler BEFORE the (potentially long) rendezvous below, so a
        # membership change during startup sets the flag instead of
        # killing the process with the default disposition.
        if cfg.elastic:
            from ..elastic.worker import _install_sigusr1_handler

            _install_sigusr1_handler()

        # CPU-simulation mode (hvtpurun --cpu-devices N): this sandbox's
        # sitecustomize pre-imports jax with the TPU platform pinned, so
        # env vars are read too early — the override must go through
        # jax.config before any backend touch.
        if cfg.cpu_devices > 0:
            force_cpu_devices(cfg.cpu_devices)

        # Multi-process launch (set up by hvtpurun, like HOROVOD_RANK/SIZE
        # env from the reference launcher): join the JAX coordination
        # service — the TPU-native replacement for the Gloo HTTP
        # rendezvous KV store (horovod/runner/http/http_server.py).
        # NOTE: this must happen before anything touches the XLA backend
        # (jax.devices()/process_count() would lock in a local-only view),
        # so membership is decided from config + coordination-client
        # state alone.
        if cfg.size > 1 and not _coordination_client_active():
            if not cfg.coordinator_addr:
                raise ValueError(
                    "HVTPU_SIZE > 1 but HVTPU_COORDINATOR_ADDR is unset; "
                    "launch with hvtpurun or set coordinator env vars"
                )
            from ..obs import metrics as _metrics

            # Cross-process CPU collectives: newer jax defaults the CPU
            # backend to gloo; jax < 0.5 needs it requested explicitly
            # or multi-process XLA programs fail at dispatch.
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo")
            except (AttributeError, ValueError):
                pass  # newer jax: gloo is already the CPU default

            _t_rdv = time.monotonic()
            jax.distributed.initialize(
                coordinator_address=(
                    f"{cfg.coordinator_addr}:{cfg.coordinator_port}"
                ),
                num_processes=cfg.size,
                process_id=cfg.rank,
                initialization_timeout=int(cfg.start_timeout),
            )
            _metrics.histogram(
                "hvtpu_rendezvous_seconds",
                "Coordination-service rendezvous duration at init "
                "(per incarnation; elastic restarts re-observe it).",
            ).observe(time.monotonic() - _t_rdv)
            _state.distributed_initialized_by_us = True

        _state.config = cfg
        _state.rank = jax.process_index()
        _state.size = jax.process_count()

        # Fault-injection harness (core/faults.py): armed once the true
        # rank is known so rank-selected clauses bind correctly.  A
        # malformed HVTPU_FAULT_SPEC fails init loudly (FaultSpecError)
        # — a chaos run that silently tests nothing is worse than one
        # that refuses to start.
        if cfg.fault_spec:
            from . import faults as _faults

            _faults.install_from_config(cfg, _state.rank)

        # Below-WARNING levels need a real handler: Python's lastResort
        # handler only emits WARNING+, so an explicit HVTPU_LOG_LEVEL of
        # info/debug would otherwise be silently inert (the reference's
        # LOG() always writes to stderr when HOROVOD_LOG_LEVEL allows).
        # Runs AFTER rank resolution so the label is the true rank even
        # when the user initialized jax.distributed themselves, and only
        # when the app has configured no logging of its own anywhere on
        # the hierarchy (hasHandlers walks ancestors) — an app-routed
        # sink keeps receiving hvtpu records via normal propagation.
        if _level < _logging.WARNING and not _logger.hasHandlers():
            _h = _logging.StreamHandler()
            _h.setFormatter(_logging.Formatter(
                f"[hvtpu rank {_state.rank}] %(levelname)s %(message)s"
            ))
            _logger.addHandler(_h)
            _logger.propagate = False
        # local/cross topology comes from the launcher when present;
        # single-host default is local == world.
        if cfg.size > 1:
            _state.local_rank = cfg.local_rank
            _state.local_size = cfg.local_size
            _state.cross_rank = cfg.cross_rank
            _state.cross_size = cfg.cross_size
        else:
            _state.local_rank = _state.rank
            _state.local_size = _state.size
            _state.cross_rank = 0
            _state.cross_size = 1

        _state.topology = Topology()
        _state.process_set_table = ProcessSetTable(
            _state.topology, _state.size
        )

        # Pod shape (P processes x D>1 local devices): say the quiet
        # part out loud — eager collectives are process-granularity
        # (one rank = one process, contribution on the first local
        # device); the other local devices serve the jit/SPMD path
        # over world_mesh().  Without this note a user could read
        # "2 of 8 devices active" off a profile of an eager-only
        # program and suspect a bug.
        if _state.size > 1:
            n_local = _state.topology.num_local_devices
            if n_local > 1:
                lanes = (
                    f"allreduce streams over all {n_local} local "
                    "lanes, other ops use the first local device"
                    if cfg.eager_multidevice
                    and not cfg.hierarchical_allreduce
                    else "transport device = first local device"
                )
                _logging.getLogger("horovod_tpu").info(
                    "pod shape: %d processes x %d local devices; eager "
                    "collectives run at process granularity (rank = "
                    "process; %s); the jit/SPMD path (world_mesh + "
                    "shard_map) engages all %d devices",
                    _state.size, n_local, lanes,
                    _state.size * n_local,
                )

        # Always-on telemetry (obs/metrics.py): identity gauges for the
        # cluster view, plus the Prometheus endpoint when enabled.  The
        # worker-count gauge is the per-rank view of the live world —
        # summed by metrics.aggregate, it is the cluster worker count;
        # an elastic relaunch re-initializes it at the new world size.
        import os as _os

        from ..obs import metrics as _metrics

        _metrics.gauge(
            "hvtpu_elastic_workers",
            "Live worker (rank) count of this incarnation's world as "
            "seen by this rank.",
        ).set(_state.size)
        _metrics.gauge(
            "hvtpu_elastic_generation",
            "Elastic incarnation counter (0 = first launch; bumps on "
            "every driver relaunch).",
        ).set(int(_os.environ.get("HVTPU_ELASTIC_GENERATION", "0") or 0))
        # HVTPU_METRICS_PORT (or --metrics-port): each worker binds
        # port + local_rank so multi-slot hosts don't collide.
        _metrics.serve_from_env(local_rank=_state.local_rank)

        if cfg.timeline_filename:
            from ..obs.timeline import Timeline

            _state.timeline = Timeline(
                cfg.timeline_filename,
                _state.rank,
                mark_cycles=cfg.timeline_mark_cycles,
            )
        if cfg.trace_dir:
            # Cross-rank distributed tracing (obs/tracing.py): per-rank
            # span files + a KV clock handshake so tools/hvtputrace can
            # merge them onto one clock.  Any failure disables tracing
            # rather than failing init.
            try:
                from ..obs import tracing as _tracing

                _client = None
                if _state.size > 1:
                    try:
                        from jax._src import distributed as _jd

                        _client = _jd.global_state.client
                        if _client is not None:
                            from .retry import fenced_kv

                            _client = fenced_kv(
                                _client, rank=_state.rank)
                    except Exception:
                        _client = None
                _tracing.install(
                    cfg.trace_dir, rank=_state.rank, size=_state.size,
                    client=_client, pings=cfg.trace_clock_pings)
            except Exception:
                _logging.getLogger("horovod_tpu").warning(
                    "distributed tracing disabled: install failed",
                    exc_info=True)
        if cfg.elastic:
            # Graceful-preemption watcher (core/preempt.py): catch the
            # configured preemption signal / notice file and run the
            # coordinated drain protocol over the coordination KV.
            # Failure degrades to plain SIGTERM death, not a broken
            # init.
            try:
                from . import preempt as _preempt

                _pclient = None
                if _state.size > 1:
                    try:
                        from jax._src import distributed as _jd

                        _pclient = _jd.global_state.client
                        if _pclient is not None:
                            from .journal import default_journal
                            from .retry import fenced_kv

                            # The drain coordinator authors DURABLE
                            # keys (accounting a relaunch must see):
                            # fence its writes and journal them for
                            # replay into a fresh coordinator.
                            _pclient = fenced_kv(
                                _pclient, rank=_state.rank,
                                journal=default_journal(_state.rank))
                    except Exception:
                        _pclient = None
                _preempt.install(
                    cfg, rank=_state.rank, size=_state.size,
                    client=_pclient)
                _replay_journal(_pclient, _state.rank)
            except Exception:
                _logging.getLogger("horovod_tpu").warning(
                    "graceful preemption disabled: install failed",
                    exc_info=True)
        # Live /debug job identity (rank/world/elastic generation).
        _metrics.register_debug_provider("job", _job_debug_state)
        # Overlap profiler (obs/stepprof): per-step exposed-comm /
        # overlap / MFU metrics plus a /debug provider.  Collection is
        # passive (comm windows + step boundaries); HVTPU_STEPPROF=0
        # disables it.
        try:
            from ..obs import stepprof as _stepprof

            if _stepprof.ACTIVE:
                _stepprof.install()
        except Exception:
            pass
        # Flight recorder (obs/flight.py): always-on bounded event ring
        # + postmortem dumps on fatal paths; HVTPU_FLIGHT=0 opts out.
        # Failure degrades to no black box, never a broken init.
        try:
            from ..obs import flight as _flight

            if _flight.env_enabled():
                _flight.install(
                    rank=_state.rank, size=_state.size,
                    generation=int(_os.environ.get(
                        "HVTPU_ELASTIC_GENERATION", "0") or 0),
                    out_dir=(_os.environ.get("HVTPU_FLIGHT_DIR")
                             or cfg.trace_dir
                             or _os.environ.get(
                                 "HVTPU_ELASTIC_STATE_DIR")
                             or "."),
                    window=_flight.env_window())
        except Exception:
            _logging.getLogger("horovod_tpu").warning(
                "flight recorder disabled: install failed",
                exc_info=True)
        # Online anomaly detection (obs/anomaly.py): robust-z detectors
        # over the step/comm/skew series; HVTPU_ANOMALY=0 opts out.
        try:
            from ..obs import anomaly as _anomaly

            if _anomaly.env_enabled():
                _anomaly.install(rank=_state.rank, size=_state.size)
        except Exception:
            _logging.getLogger("horovod_tpu").warning(
                "anomaly detection disabled: install failed",
                exc_info=True)
        # Fleet health publisher (fleet/health.py): when this worker
        # belongs to a fleet job (HVTPU_FLEET_JOB, injected by the
        # fleet runner), rank 0 publishes a compact health summary
        # under the job's prefixed KV namespace each interval.
        if _state.rank == 0 and _os.environ.get("HVTPU_FLEET_JOB"):
            try:
                _hclient = None
                if _state.size >= 1:
                    try:
                        from jax._src import distributed as _jd

                        _hclient = _jd.global_state.client
                        if _hclient is not None:
                            # Fenced so a superseded zombie can never
                            # publish a stale health summary; the
                            # arbiter-side reader is stamp-tolerant
                            # (fleet/health.py uses core.retry.unstamp).
                            from .retry import fenced_kv

                            _hclient = fenced_kv(
                                _hclient, rank=_state.rank)
                    except Exception:
                        _hclient = None
                # A KV client is optional: without one the reporter
                # still mirrors summaries to HVTPU_FLEET_HEALTH_DIR
                # (the file channel the arbiter actually polls — it is
                # not a member of this job's coordination world).
                if (_hclient is not None
                        or _os.environ.get("HVTPU_FLEET_HEALTH_DIR")):
                    from ..fleet import health as _health

                    _state.health_reporter = _health.HealthReporter(
                        _hclient,
                        _os.environ["HVTPU_FLEET_JOB"],
                        rank=_state.rank)
                    _state.health_reporter.start()
            except Exception:
                _logging.getLogger("horovod_tpu").warning(
                    "fleet health publisher disabled: install failed",
                    exc_info=True)
        if cfg.autotune:
            from ..obs.autotune import Autotuner

            _state.autotuner = Autotuner(cfg)

        _state.init_generation += 1
        _state.initialized = True
        atexit.register(_shutdown_at_exit)
        return _state


def shutdown():
    """Tear down (parity: ``horovod_shutdown``)."""
    with _init_lock:
        if not _state.initialized:
            return
        if _state.controller is not None:
            try:
                _state.controller.stop()
            except Exception:
                pass
            _state.controller = None
        if _state.timeline is not None:
            try:
                _state.timeline.close()
            except Exception:
                pass
            _state.timeline = None
        # Flush trace files BEFORE the coordination client goes away
        # (and from _shutdown_at_exit on abnormal exits) so traces
        # survive; uninstall is idempotent.
        try:
            from ..obs import tracing as _tracing

            _tracing.uninstall()
        except Exception:
            pass
        # Stop the fleet health publisher BEFORE the coordination
        # client goes away (its loop writes the fleet KV namespace).
        if _state.health_reporter is not None:
            try:
                _state.health_reporter.stop()
            except Exception:
                pass
            _state.health_reporter = None
        # Anomaly engine + flight recorder: uninstall is idempotent and
        # restores the SIGUSR2 handler; the ring is dropped (postmortems
        # only exist for fatal paths, not clean shutdowns).
        try:
            from ..obs import anomaly as _anomaly

            _anomaly.uninstall()
        except Exception:
            pass
        try:
            from ..obs import flight as _flight

            _flight.uninstall()
        except Exception:
            pass
        _state.autotuner = None
        try:
            from ..obs import metrics as _m

            _m.unregister_debug_provider("job")
        except Exception:
            pass
        try:
            from ..obs import stepprof as _stepprof

            _stepprof.uninstall()
        except Exception:
            pass
        try:
            from ..obs import metrics as _metrics

            _metrics.stop_http_server()
        except Exception:
            pass
        # Stop the preemption watcher before the client goes away (its
        # poll loop reads the coordination KV); uninstall is idempotent
        # and restores the previous signal handler.
        try:
            from . import preempt as _preempt

            _preempt.uninstall()
        except Exception:
            pass
        # The stall inspector's stop posts a goodbye tombstone over the
        # coordination KV (so still-running peers don't blame this
        # rank for a stall) — it must run BEFORE the client goes away.
        try:
            from ..comm import stall as _stall

            _stall.stop(_state)
        except Exception:
            _state.sync_stall = None
        if _state.distributed_initialized_by_us:
            try:
                from ..comm.stall import poisoned as _stall_poisoned

                if _stall_poisoned():
                    # The shutdown barrier rides the coordination gRPC
                    # channel (independent of the wedged XLA
                    # execution), and joining it lets still-healthy
                    # peers finish before the leader goes away — but a
                    # peer that DIED mid-collective never arrives, so
                    # bound the wait instead of parking for the full
                    # coordination timeout.
                    _t = threading.Thread(
                        target=jax.distributed.shutdown, daemon=True)
                    _t.start()
                    _t.join(timeout=15.0)
                else:
                    jax.distributed.shutdown()
            except Exception:
                pass
            _state.distributed_initialized_by_us = False
        _state.initialized = False
        _state.sync_stall = None
        _state.config = None
        _state.topology = None
        _state.process_set_table = None
        _state.rank, _state.size = 0, 1
        _state.local_rank, _state.local_size = 0, 1
        _state.cross_rank, _state.cross_size = 0, 1


def _shutdown_at_exit():
    try:
        shutdown()
    except Exception:
        pass
    try:
        from ..comm.stall import poison_exit_status, poisoned

        if poisoned():
            # Interpreter teardown would park on the stuck collective
            # (XLA client destructor joins pending executions) —
            # hard-exit like the reference's stall shutdown does.
            # Trade-off, stated out loud since os._exit skips
            # anything registered before horovod_tpu's atexit hook
            # (LIFO): those handlers are sacrificed to avoid a
            # teardown that never finishes.
            import logging as _logging

            _logging.getLogger("horovod_tpu").critical(
                "hard-exiting past a wedged collective abandoned by "
                "the stall watchdog; atexit handlers registered "
                "before horovod_tpu will not run")
            # hard-exit like the
            # reference's stall shutdown does.  Status 0 if the
            # process re-initialized past the poisoned generation
            # (elastic recovery succeeded), 1 otherwise.
            import os as _os
            import sys as _sys

            _sys.stdout.flush()
            _sys.stderr.flush()
            _os._exit(poison_exit_status())
    except ImportError:
        pass


def add_process_set(ps) -> ProcessSet:
    st = require_init("add_process_set")
    if not isinstance(ps, ProcessSet):
        ps = ProcessSet(ps)
    st.process_set_table.add(ps)
    if st.controller is not None and ps.ranks is not None:
        st.controller.register_process_set(ps.process_set_id, ps.ranks)
    return ps


def remove_process_set(ps) -> bool:
    st = require_init("remove_process_set")
    psid = ps.process_set_id if isinstance(ps, ProcessSet) else int(ps)
    try:
        st.process_set_table.remove(psid)
        return True
    except ValueError:
        return False
