"""Pod-scale sharded checkpointing (api/sharded_checkpoint.py): every
process writes its addressable shards; restore reassembles onto the
CURRENT mesh — possibly a different process count or sharding than the
saving job (the elastic-resize resume story at pod scale, where the
reference's rank-0-writes idiom cannot go)."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu
from horovod_tpu.runner import run

_REPO_ROOT = os.path.dirname(os.path.dirname(horovod_tpu.__file__))
_ENV = {"PYTHONPATH": _REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", "")}


class TestSingleProcess:
    def _tree(self, mesh):
        rng = np.random.RandomState(0)
        w = rng.randn(16, 8).astype(np.float32)
        b = rng.randn(64).astype(np.float32)
        rows = NamedSharding(mesh, P("world"))
        repl = NamedSharding(mesh, P())
        return {
            "w": jax.device_put(w, rows),
            "nested": {"b": jax.device_put(b, repl)},
        }, {"w": w, "b": b}

    def test_roundtrip_same_sharding(self, hvt, tmp_path):
        from horovod_tpu import ShardedCheckpointer

        mesh = hvt.world_mesh()
        tree, raw = self._tree(mesh)
        ckpt = ShardedCheckpointer(str(tmp_path))
        ckpt.save(3, tree)
        assert ckpt.all_steps() == [3]
        out = ckpt.restore(tree)
        np.testing.assert_array_equal(np.asarray(out["w"]), raw["w"])
        np.testing.assert_array_equal(
            np.asarray(out["nested"]["b"]), raw["b"])

    def test_restore_onto_different_sharding(self, hvt, tmp_path):
        """Saved row-sharded, restored replicated AND column-sharded —
        the assembly path must stitch shards across layouts."""
        from horovod_tpu import ShardedCheckpointer

        mesh = hvt.world_mesh()
        tree, raw = self._tree(mesh)
        ckpt = ShardedCheckpointer(str(tmp_path))
        ckpt.save(0, tree)

        repl = NamedSharding(mesh, P())
        cols = NamedSharding(mesh, P(None, "world"))
        template = {
            "w": jax.ShapeDtypeStruct((16, 8), jnp.float32,
                                      sharding=cols),
            "nested": {"b": jax.ShapeDtypeStruct((64,), jnp.float32,
                                                 sharding=repl)},
        }
        out = ckpt.restore(template)
        assert out["w"].sharding == cols
        np.testing.assert_array_equal(np.asarray(out["w"]), raw["w"])
        np.testing.assert_array_equal(
            np.asarray(out["nested"]["b"]), raw["b"])

    def test_missing_leaf_raises(self, hvt, tmp_path):
        from horovod_tpu import ShardedCheckpointer

        mesh = hvt.world_mesh()
        tree, _ = self._tree(mesh)
        ckpt = ShardedCheckpointer(str(tmp_path))
        ckpt.save(0, tree)
        bad = dict(tree)
        bad["extra"] = tree["w"]
        with pytest.raises(KeyError, match="extra"):
            ckpt.restore(bad)


@pytest.mark.multiprocess
def test_pod_save_then_restore_on_different_topology(tmp_path):
    """2 processes x 4 devices save a world-sharded tree (each process
    writes only its 4 shards); the PARENT process (1 proc x 8 devices —
    a different topology) restores and verifies every element."""
    ckpt_dir = str(tmp_path / "ckpt")

    def body():
        import numpy as np

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        import horovod_tpu as hvt

        hvt.init()
        assert hvt.size() == 2 and jax.local_device_count() == 4
        mesh = hvt.world_mesh()
        rng = np.random.RandomState(7)
        w = rng.randn(32, 4).astype(np.float32)
        s = rng.randn(8).astype(np.float32)
        tree = {
            "w": jax.make_array_from_callback(
                w.shape, NamedSharding(mesh, P("world")),
                lambda i: w[i]),
            "s": jax.make_array_from_callback(
                s.shape, NamedSharding(mesh, P()), lambda i: s[i]),
        }
        hvt.ShardedCheckpointer(ckpt_dir).save(11, tree)
        return hvt.rank()

    import cloudpickle
    import sys

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    try:
        results = run(body, np=2, cpu_devices=4, env=_ENV,
                      start_timeout=300.0)
    finally:
        cloudpickle.unregister_pickle_by_value(sys.modules[__name__])
    assert sorted(results) == [0, 1]

    # parent: different topology (1 process, its own 8 CPU devices)
    import horovod_tpu as hvt

    hvt.init()
    try:
        from horovod_tpu import ShardedCheckpointer

        mesh = hvt.world_mesh()
        template = {
            "w": jax.ShapeDtypeStruct(
                (32, 4), jnp.float32,
                sharding=NamedSharding(mesh, P("world"))),
            "s": jax.ShapeDtypeStruct(
                (8,), jnp.float32,
                sharding=NamedSharding(mesh, P())),
        }
        ckpt = ShardedCheckpointer(ckpt_dir)
        assert ckpt.latest_step() == 11
        out = ckpt.restore(template)
        rng = np.random.RandomState(7)
        np.testing.assert_array_equal(
            np.asarray(out["w"]), rng.randn(32, 4).astype(np.float32))
        np.testing.assert_array_equal(
            np.asarray(out["s"]), rng.randn(8).astype(np.float32))
    finally:
        hvt.shutdown()


class TestDurabilitySemantics:
    def test_resave_discards_stale_pieces(self, hvt, tmp_path):
        """Re-saving a step must clear prior content: orphan pieces
        from a larger world's earlier save of the SAME step must not
        blend into the restored data."""
        from horovod_tpu import ShardedCheckpointer
        import json

        mesh = hvt.world_mesh()
        rows = NamedSharding(mesh, P("world"))
        w1 = np.arange(32, dtype=np.float32).reshape(8, 4)
        w2 = w1 + 100.0
        ckpt = ShardedCheckpointer(str(tmp_path))
        ckpt.save(0, {"w": jax.device_put(w1, rows)})

        # plant an orphan "process 9" manifest+piece overlapping rows
        step_dir = tmp_path / "step_000000000000"
        garbage = np.full((8, 4), -1.0, np.float32)
        np.save(step_dir / "pieces" / "orphan.p9.0.npy", garbage)
        key = json.load(open(step_dir / "manifest_p0.json"))
        orphan_key = next(iter(key))
        (step_dir / "manifest_p9.json").write_text(json.dumps(
            {orphan_key: [{"file": "orphan.p9.0.npy",
                           "slices": [[0, 8], [0, 4]]}]}))

        ckpt.save(0, {"w": jax.device_put(w2, rows)})
        out = ckpt.restore({"w": jax.device_put(w2, rows)})
        np.testing.assert_array_equal(np.asarray(out["w"]), w2)

    def test_step_without_commit_marker_is_invisible(self, hvt,
                                                     tmp_path):
        """A step dir missing meta.json (a rank died mid-save) must be
        skipped by all_steps/latest_step so resume falls back to the
        last intact checkpoint."""
        from horovod_tpu import ShardedCheckpointer

        mesh = hvt.world_mesh()
        rows = NamedSharding(mesh, P("world"))
        w = np.ones((8, 4), np.float32)
        tree = {"w": jax.device_put(w, rows)}
        ckpt = ShardedCheckpointer(str(tmp_path))
        ckpt.save(1, tree)
        # half-written step 2: pieces but no commit marker
        (tmp_path / "step_000000000002" / "pieces").mkdir(parents=True)
        assert ckpt.all_steps() == [1]
        assert ckpt.latest_step() == 1
        out = ckpt.restore(tree)
        np.testing.assert_array_equal(np.asarray(out["w"]), w)

    def test_host_leaf_takes_rank0_value_once(self, hvt, tmp_path):
        """Plain numpy leaves are written once (rank 0), not once per
        process, and roundtrip exactly."""
        from horovod_tpu import ShardedCheckpointer
        import json

        mesh = hvt.world_mesh()
        rows = NamedSharding(mesh, P("world"))
        tree = {
            "w": jax.device_put(np.ones((8, 2), np.float32), rows),
            "host_counter": np.int64(42),
        }
        ckpt = ShardedCheckpointer(str(tmp_path))
        ckpt.save(0, tree)
        manifest = json.load(open(
            tmp_path / "step_000000000000" / "manifest_p0.json"))
        host_entries = [e for k, es in manifest.items() for e in es
                        if e["file"].endswith(".host.npy")]
        assert len(host_entries) == 1
        out = ckpt.restore(tree)
        assert int(np.asarray(out["host_counter"])) == 42


@pytest.mark.multiprocess
def test_sharded_elastic_state_resync_across_topologies(tmp_path):
    """ShardedJaxState: a 2-proc x 2-dev world commits GLOBAL sharded
    arrays (JaxState's np.asarray path would raise on them); a
    'restarted' trainer on a different topology (parent: 1 proc x 8
    devs) constructs fresh state and sync() reassembles the committed
    values onto the new world's shardings."""
    state_dir = str(tmp_path / "elastic_state")

    def body():
        import os

        import numpy as np

        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        import horovod_tpu as hvt
        import horovod_tpu.elastic as elastic

        os.environ["HVTPU_ELASTIC_STATE_DIR"] = state_dir
        hvt.init()
        assert hvt.size() == 2 and jax.local_device_count() == 2
        mesh = hvt.world_mesh()
        w = np.arange(32, dtype=np.float32).reshape(8, 4)
        state = elastic.ShardedJaxState(
            params={"w": jax.make_array_from_callback(
                w.shape, NamedSharding(mesh, P("world")),
                lambda i: w[i])},
            epoch=0,
        )
        state.epoch = 3
        state.params = {"w": state.params["w"] * 2.0}
        state.commit()
        return hvt.rank()

    import cloudpickle
    import sys

    cloudpickle.register_pickle_by_value(sys.modules[__name__])
    try:
        results = run(body, np=2, cpu_devices=2, env=_ENV,
                      start_timeout=300.0)
    finally:
        cloudpickle.unregister_pickle_by_value(sys.modules[__name__])
    assert sorted(results) == [0, 1]

    # "restarted" world: the parent process with its own 8-dev mesh
    import horovod_tpu as hvt
    import horovod_tpu.elastic as elastic

    os.environ["HVTPU_ELASTIC_STATE_DIR"] = state_dir
    hvt.init()
    try:
        mesh = hvt.world_mesh()
        fresh = {"w": jax.device_put(
            np.zeros((8, 4), np.float32),
            NamedSharding(mesh, P("world")))}
        state = elastic.ShardedJaxState(params=fresh, epoch=0)
        state.sync()
        assert state.epoch == 3
        want = np.arange(32, dtype=np.float32).reshape(8, 4) * 2.0
        np.testing.assert_array_equal(
            np.asarray(state.params["w"]), want)
    finally:
        os.environ.pop("HVTPU_ELASTIC_STATE_DIR", None)
        hvt.shutdown()


def test_replicated_vs_sharded_attr_split_across_resize(hvt,
                                                        tmp_path,
                                                        monkeypatch):
    """PR 15 edge case: one attr replicated (P()), one sharded
    (P("world")), one plain — committed, then restored into a fresh
    state whose arrays carry DIFFERENT shardings (the resized world's
    layout).  The split must route each attr down the right plane:
    arrays reassemble onto the new shardings, the plain attr rides the
    rank-0 pickle."""
    import horovod_tpu.elastic as elastic

    monkeypatch.setenv("HVTPU_ELASTIC_STATE_DIR", str(tmp_path))
    mesh = hvt.world_mesh()
    rows = NamedSharding(mesh, P("world"))
    repl = NamedSharding(mesh, P())
    w = np.arange(64, dtype=np.float32).reshape(8, 8)
    m = np.arange(16, dtype=np.float32)
    state = elastic.ShardedJaxState(
        params={"w": jax.device_put(w, rows)},
        ema={"m": jax.device_put(m, repl)},
        epoch=0,
    )
    state.epoch = 5
    state.commit()
    state.wait_durable()

    # the "resized" trainer: same global shapes, swapped layouts —
    # params now replicated, ema now sharded
    cols = NamedSharding(mesh, P(None, "world"))
    fresh = elastic.ShardedJaxState(
        params={"w": jax.device_put(np.zeros((8, 8), np.float32),
                                    cols)},
        ema={"m": jax.device_put(np.zeros(16, np.float32), rows)},
        epoch=0,
    )
    fresh.sync()
    assert fresh.epoch == 5
    assert fresh.params["w"].sharding == cols
    assert fresh.ema["m"].sharding == rows
    np.testing.assert_array_equal(np.asarray(fresh.params["w"]), w)
    np.testing.assert_array_equal(np.asarray(fresh.ema["m"]), m)


def test_sharded_state_sync_rejects_missing_array_template(hvt,
                                                           tmp_path,
                                                           monkeypatch):
    """A committed array attribute whose fresh value holds no jax.Array
    must fail sync loudly instead of silently keeping zeros."""
    import horovod_tpu.elastic as elastic

    monkeypatch.setenv("HVTPU_ELASTIC_STATE_DIR", str(tmp_path))
    mesh = hvt.world_mesh()
    w = jax.device_put(np.ones((8, 2), np.float32),
                       NamedSharding(mesh, P("world")))
    state = elastic.ShardedJaxState(params={"w": w}, epoch=1)
    state.commit()

    fresh = elastic.ShardedJaxState(params=None, epoch=0)
    with pytest.raises(ValueError, match="params"):
        fresh.sync()
