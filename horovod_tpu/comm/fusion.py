"""Tensor fusion: bucketed flat-buffer collectives.

Parity surface: ``horovod/common/fusion_buffer_manager.cc``
(``FusionBufferManager::InitializeBuffer/GetBuffer``) and the fusion
step of the controller (``Controller::FuseResponses``): small tensors
are packed into one flat buffer so each cycle issues one collective
instead of hundreds, with a deterministic packing order identical on
every rank.

TPU-native re-expression: the "buffer" is not a persistent allocation we
memcpy around — inside jit, the flatten/concat/cast and the unpack are
XLA ops that fuse with the producing/consuming computation in HBM, and
the single ``psum`` per bucket rides ICI.  What we keep from the
reference is the *semantics*: deterministic ordering (sorted tensor
names, as ``FuseResponses`` orders responses), a byte threshold
(``HVTPU_FUSION_THRESHOLD``), and one collective per bucket.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .compression import NoneCompressor
from .reduce_ops import ReduceOp, normalize_op


@dataclasses.dataclass(frozen=True)
class BucketEntry:
    name: str
    index: int          # position in the original flat list
    shape: Tuple[int, ...]
    dtype: Any
    size: int           # element count
    nbytes: int


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    buckets: Tuple[Tuple[BucketEntry, ...], ...]

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)


def plan_buckets(
    names: Sequence[str],
    leaves: Sequence[Any],
    threshold_bytes: int,
) -> BucketPlan:
    """Greedy size-bounded bucketing in deterministic (sorted-name) order.

    A tensor larger than the threshold gets its own bucket (the reference
    does the same: responses above the fusion threshold go alone).
    """
    entries = []
    for i, (name, leaf) in enumerate(zip(names, leaves)):
        shape = tuple(leaf.shape)
        dtype = jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") else leaf.dtype
        size = 1
        for d in shape:
            size *= d
        nbytes = size * jnp.dtype(dtype).itemsize
        entries.append(BucketEntry(name, i, shape, dtype, size, nbytes))
    entries.sort(key=lambda e: e.name)

    buckets: List[List[BucketEntry]] = []
    cur: List[BucketEntry] = []
    cur_bytes = 0
    for e in entries:
        if cur and cur_bytes + e.nbytes > threshold_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(e)
        cur_bytes += e.nbytes
        if e.nbytes > threshold_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return BucketPlan(tuple(tuple(b) for b in buckets))


def plan_for_tree(tree, threshold_bytes: int) -> Tuple[BucketPlan, Any]:
    """Bucket plan for a pytree of tensors; names come from the treedef
    paths, so ordering is deterministic across ranks for identical trees
    (the analog of the reference keying fusion on tensor names).
    """
    leaves_with_paths = jax.tree_util.tree_leaves_with_path(tree)
    names = [jax.tree_util.keystr(path) for path, _ in leaves_with_paths]
    leaves = [leaf for _, leaf in leaves_with_paths]
    treedef = jax.tree_util.tree_structure(tree)
    return plan_buckets(names, leaves, threshold_bytes), treedef


def fused_tree_allreduce(
    tree,
    *,
    axis_name: str,
    threshold_bytes: int,
    op: Optional[ReduceOp] = None,
    average: Optional[bool] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    compression=NoneCompressor,
    groups: Optional[List[List[int]]] = None,
    plan: Optional[BucketPlan] = None,
):
    """Allreduce every leaf of a pytree with bucketed fusion, inside jit.

    This is the gradient hot path used by ``DistributedOptimizer``: one
    flatten + one wire-cast + one ``psum`` per bucket.  Returns a tree of
    the same structure.
    """
    rop = normalize_op(op, average)
    leaves_with_paths = jax.tree_util.tree_leaves_with_path(tree)
    names = [jax.tree_util.keystr(p) for p, _ in leaves_with_paths]
    leaves = [l for _, l in leaves_with_paths]
    treedef = jax.tree_util.tree_structure(tree)
    if plan is None:
        plan = plan_buckets(names, leaves, threshold_bytes)

    if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
        raise ValueError("fused_tree_allreduce supports Sum/Average/Adasum")

    from . import spmd
    from .packing import pack_flat, unpack_flat

    out_leaves: List[Any] = [None] * len(leaves)
    for bucket in plan.buckets:
        flat, _ = pack_flat([leaves[e.index] for e in bucket])
        # Per-tensor segment boundaries keep Adasum's dot products
        # per-tensor inside the fused buffer (reference: tensor_counts
        # in adasum.h DispatchFusedAllreduce) — results must not depend
        # on the fusion threshold.
        segments = []
        off = 0
        for e in bucket:
            segments.append((off, e.size))
            off += e.size
        # spmd.allreduce handles op routing (incl. the Adasum+groups and
        # int8 rejection paths) so fused and unfused semantics agree.
        red = spmd.allreduce(
            flat,
            axis_name=axis_name,
            op=rop,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            compression=compression,
            groups=groups,
            adasum_segments=segments if rop == ReduceOp.ADASUM else None,
        )
        specs = [(e.shape, e.dtype, e.size) for e in bucket]
        for e, out in zip(bucket, unpack_flat(red, specs)):
            out_leaves[e.index] = out

    return jax.tree_util.tree_unflatten(treedef, out_leaves)
