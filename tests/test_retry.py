"""core/retry.py: the bounded-backoff engine, named policies, and the
resilient coordination-KV wrapper (fault sites kv.get / kv.put)."""

import threading

import pytest

from horovod_tpu.core import faults, retry
from horovod_tpu.obs import metrics as obs_metrics


class Flaky:
    """Callable failing the first N calls with a given exception."""

    def __init__(self, fails, exc):
        self.fails = fails
        self.exc = exc
        self.calls = 0

    def __call__(self, *a):
        self.calls += 1
        if self.calls <= self.fails:
            raise self.exc
        return "ok"


def _fast(attempts=4, retryable=lambda e: True, **kw):
    return retry.RetryPolicy(name="t", max_attempts=attempts,
                             base_delay_s=0.0, retryable=retryable, **kw)


class TestCall:
    def test_succeeds_after_transient_failures(self):
        fn = Flaky(2, TimeoutError("x"))
        assert retry.call(_fast(), fn) == "ok"
        assert fn.calls == 3

    def test_exhaustion_reraises_original_error(self):
        fn = Flaky(10, TimeoutError("boom"))
        with pytest.raises(TimeoutError, match="boom"):
            retry.call(_fast(attempts=3), fn)
        assert fn.calls == 3

    def test_non_retryable_raises_immediately(self):
        fn = Flaky(10, ValueError("nope"))
        policy = _fast(retryable=lambda e: isinstance(e, TimeoutError))
        with pytest.raises(ValueError):
            retry.call(policy, fn)
        assert fn.calls == 1

    def test_deadline_bounds_the_loop(self):
        import time

        fn = Flaky(10**6, TimeoutError("x"))
        policy = retry.RetryPolicy(
            name="t", max_attempts=10**6, base_delay_s=0.01,
            max_delay_s=0.01, deadline_s=0.1, retryable=lambda e: True)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            retry.call(policy, fn)
        assert time.monotonic() - t0 < 2.0

    def test_result_based_retry(self):
        seen = []

        def fn():
            seen.append(1)
            return len(seen)

        policy = retry.RetryPolicy(
            name="t", max_attempts=5, base_delay_s=0.0,
            retry_result=lambda r: r < 3)
        assert retry.call(policy, fn) == 3

    def test_result_retry_returns_final_value_on_exhaustion(self):
        policy = retry.RetryPolicy(
            name="t", max_attempts=2, base_delay_s=0.0,
            retry_result=lambda r: True)
        assert retry.call(policy, lambda: "still-bad") == "still-bad"

    def test_on_retry_callback_counts(self):
        hits = []
        fn = Flaky(2, TimeoutError("x"))
        retry.call(_fast(), fn,
                   on_retry=lambda attempt, exc: hits.append(attempt))
        assert hits == [1, 2]

    def test_backoff_is_capped_full_jitter(self):
        import random

        policy = retry.RetryPolicy(name="t", max_attempts=10,
                                   base_delay_s=0.1, max_delay_s=0.5)
        rng = random.Random(7)
        for attempt in range(1, 10):
            s = policy.backoff_s(attempt, rng)
            assert 0.0 <= s <= 0.5

    def test_decorator_form(self):
        calls = []

        @retry.retrying(_fast())
        def fn():
            calls.append(1)
            if len(calls) < 2:
                raise TimeoutError("x")
            return 42

        assert fn() == 42


class TestPolicies:
    def test_kv_retryable_classification(self):
        assert retry.kv_retryable(TimeoutError("t"))
        assert retry.kv_retryable(RuntimeError("UNAVAILABLE: conn"))
        assert retry.kv_retryable(RuntimeError("DEADLINE_EXCEEDED"))
        # a missing key is an ANSWER, not a transient failure
        assert not retry.kv_retryable(KeyError("NOT_FOUND: k"))
        assert not retry.kv_retryable(ValueError("bad arg"))
        # the blocking-get variant polls through NOT_FOUND
        assert retry.kv_blocking_retryable(RuntimeError("NOT_FOUND: k"))

    def test_kv_policy_env_knobs(self, monkeypatch):
        monkeypatch.setenv("HVTPU_KV_RETRY_ATTEMPTS", "7")
        monkeypatch.setenv("HVTPU_KV_RETRY_BASE_MS", "10")
        p = retry.kv_policy()
        assert p.max_attempts == 7
        assert p.base_delay_s == pytest.approx(0.01)

    def test_gloo_policy_markers(self):
        assert retry.is_gloo_infra_error("x Connection closed by peer y")
        assert retry.is_gloo_infra_error("collective transport failure")
        assert not retry.is_gloo_infra_error("assert 1 == 2")
        assert retry.GLOO_TEARDOWN.max_attempts == 5
        # injected faults say UNAVAILABLE — an infra retry must NOT
        # swallow them (they are the thing under test in chaos runs)
        assert not retry.is_gloo_infra_error("UNAVAILABLE (hvtpu "
                                             "injected fault: ...)")


class FlakyKV:
    """Coordination-client fake whose ops fail transiently N times."""

    def __init__(self, fails=0):
        self.d = {}
        self.fails = fails
        self.lock = threading.Lock()

    def _maybe_fail(self):
        with self.lock:
            if self.fails > 0:
                self.fails -= 1
                raise RuntimeError("UNAVAILABLE: coordinator blip")

    def key_value_set(self, k, v):
        self._maybe_fail()
        self.d[k] = v

    def key_value_try_get(self, k):
        self._maybe_fail()
        if k not in self.d:
            raise KeyError(f"NOT_FOUND: {k}")
        return self.d[k]

    def key_value_dir_get(self, prefix):
        self._maybe_fail()
        return [(k, v) for k, v in self.d.items()
                if k.startswith(prefix)]

    def key_value_delete(self, k):
        self.d.pop(k, None)


class TestResilientKV:
    def _kv(self, fails=0):
        fake = FlakyKV(fails)
        policy = retry.RetryPolicy(name="kv-test", max_attempts=4,
                                   base_delay_s=0.0,
                                   retryable=retry.kv_retryable)
        return fake, retry.ResilientKV(fake, rank=0, policy=policy)

    def test_put_survives_transient_unavailable(self):
        fake, kv = self._kv(fails=2)
        before = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_retries_total").value()
        kv.key_value_set("a", "1")
        assert fake.d == {"a": "1"}
        after = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_retries_total").value()
        assert after - before == 2

    def test_exhaustion_counts_and_reraises(self):
        fake, kv = self._kv(fails=50)
        before = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_retry_exhausted_total").value()
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            kv.key_value_set("a", "1")
        after = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_retry_exhausted_total").value()
        assert after - before == 1

    def test_miss_is_not_retried(self):
        fake, kv = self._kv()
        with pytest.raises(KeyError):
            kv.key_value_try_get("missing")

    def test_dir_get_presence_mirrors_client(self):
        fake, kv = self._kv()
        assert getattr(kv, "key_value_dir_get", None) is not None
        kv.key_value_set("p/x", "1")
        assert kv.key_value_dir_get("p/") == [("p/x", "1")]

        class NoDir:
            def key_value_set(self, k, v):
                pass

        bare = retry.ResilientKV(NoDir())
        # comm/stall.py picks strict mode off this exact probe
        assert getattr(bare, "key_value_dir_get", None) is None

    def test_idempotent_wrap(self):
        fake, kv = self._kv()
        assert retry.resilient_kv(kv) is kv
        assert retry.resilient_kv(None) is None

    def test_injected_drop_semantics(self):
        fake, kv = self._kv()
        faults.install("kv.put:drop@count=1,times=1; "
                       "kv.get:drop@count=1,times=1", rank=0)
        try:
            kv.key_value_set("a", "1")       # dropped
            assert fake.d == {}
            fake.d["b"] = "2"
            with pytest.raises(KeyError):    # dropped read = miss
                kv.key_value_try_get("b")
            # budgets spent: subsequent ops flow normally
            kv.key_value_set("c", "3")
            assert fake.d["c"] == "3"
            assert kv.key_value_try_get("b") == "2"
        finally:
            faults.uninstall()

    def test_injected_error_is_retried_to_success(self):
        """An error-injected KV op carries the UNAVAILABLE marker, so
        the retry policy heals it — the self-healing loop end to end."""
        fake, kv = self._kv()
        faults.install("kv.put:error@count=1,times=1", rank=0)
        try:
            kv.key_value_set("a", "1")
            assert fake.d == {"a": "1"}
        finally:
            faults.uninstall()


class TestFencedKV:
    """Generation-fenced wrapper: stamping, stale-write rejection,
    beacon supersession, and fake-clock lease expiry (no real
    sleeps)."""

    def _fenced(self, fake=None, rank=0, generation=0, **kw):
        fake = FlakyKV() if fake is None else fake
        exits = []
        kv = retry.FencedKV(fake, rank=rank, job_epoch=0,
                            generation=generation,
                            exit_fn=exits.append, **kw)
        return fake, kv, exits

    def test_stamp_roundtrip(self):
        fake, kv, _ = self._fenced(generation=3)
        kv.key_value_set("k", "payload")
        raw = fake.d["k"]
        assert raw.startswith("\x1fF0.3\x1f")
        token, payload = retry.unstamp(raw)
        assert token == (0, 3) and payload == "payload"
        assert kv.key_value_try_get("k") == "payload"

    def test_unstamp_tolerates_unstamped_and_malformed(self):
        assert retry.unstamp("plain") == (None, "plain")
        assert retry.unstamp(None) == (None, None)
        assert retry.unstamp(b"bytes") == (None, b"bytes")
        assert retry.unstamp("\x1fFnope\x1fv") == (None, "\x1fFnope\x1fv")
        assert retry.unstamp("\x1fF1.2") == (None, "\x1fF1.2")

    def test_reader_rejects_stale_generation(self):
        fake, kv, _ = self._fenced(generation=2)
        before = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_fenced_writes_total").value()
        fake.d["z"] = "\x1fF0.1\x1fstale"   # older writer's value
        with pytest.raises(KeyError, match="fenced stale write"):
            kv.key_value_try_get("z")
        after = obs_metrics.REGISTRY.counter(
            "hvtpu_kv_fenced_writes_total").value()
        assert after - before == 1

    def test_dir_get_skips_stale_entries(self):
        fake, kv, _ = self._fenced(generation=2)
        kv.key_value_set("p/live", "good")
        fake.d["p/old"] = "\x1fF0.0\x1fstale"
        fake.d["p/plain"] = "legacy"        # unstamped: passes through
        entries = dict(kv.key_value_dir_get("p/"))
        assert entries == {"p/live": "good", "p/plain": "legacy"}

    def test_beacon_supersession_fences_old_writer(self):
        fake = FlakyKV()
        _, old, old_exits = self._fenced(fake, rank=0, generation=0)
        old.key_value_set("k", "v0")
        _, new, new_exits = self._fenced(fake, rank=1, generation=1)
        assert fake.d[retry.FENCE_BEACON_KEY] == "0.1"
        # force the old writer to re-check the beacon on its next op
        old._recheck = True
        with pytest.raises(retry.FencedError, match="superseded"):
            old.key_value_set("k", "stale")
        assert old_exits == [retry.FENCE_EXIT_CODE]
        assert new_exits == []
        # the stale write never reached the store
        assert retry.unstamp(fake.d["k"])[1] == "v0"
        # a fenced client refuses every further op
        with pytest.raises(retry.FencedError):
            old.key_value_try_get("k")

    def test_lease_expiry_fences_on_fake_clock(self):
        from horovod_tpu.core import clock as core_clock

        class FakeClock(core_clock.Clock):
            def __init__(self):
                self.t = 100.0

            def monotonic(self):
                return self.t

            def wall(self):
                return self.t

            def sleep(self, seconds):
                self.t += max(0.0, seconds)

            def call_later(self, delay_s, fn):
                return core_clock.Timer()

        class DownKV(FlakyKV):
            def key_value_set(self, k, v):
                raise RuntimeError("UNAVAILABLE: host gone")

        fc = FakeClock()
        core_clock.install(fc)
        try:
            fake = DownKV()
            fake.d[retry.FENCE_BEACON_KEY] = "0.0"
            policy = retry.RetryPolicy(
                name="kv-test", max_attempts=2, base_delay_s=0.0,
                retryable=retry.kv_retryable)
            exits = []
            kv = retry.FencedKV(fake, rank=0, job_epoch=0,
                                generation=0, lease_s=5.0,
                                policy=policy, exit_fn=exits.append)
            assert kv.lease_remaining() == pytest.approx(5.0)
            # unreachable but inside the lease: op fails, no fence
            with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                kv.key_value_set("a", "1")
            assert exits == []
            fc.t += 6.0   # lease expires with zero server contact
            before = obs_metrics.REGISTRY.counter(
                "hvtpu_fence_exits_total").value()
            with pytest.raises(retry.FencedError, match="lease expired"):
                kv.key_value_set("a", "2")
            assert exits == [retry.FENCE_EXIT_CODE]
            after = obs_metrics.REGISTRY.counter(
                "hvtpu_fence_exits_total").value()
            assert after - before == 1
        finally:
            core_clock.install(None)

    def test_lease_survives_not_found_answers(self):
        """NOT_FOUND proves the server answered: the lease refreshes
        even though the op 'failed'."""
        from horovod_tpu.core import clock as core_clock

        class FakeClock(core_clock.Clock):
            def __init__(self):
                self.t = 0.0

            def monotonic(self):
                return self.t

            def wall(self):
                return self.t

            def sleep(self, seconds):
                self.t += max(0.0, seconds)

            def call_later(self, delay_s, fn):
                return core_clock.Timer()

        fc = FakeClock()
        core_clock.install(fc)
        try:
            fake = FlakyKV()
            fake.d[retry.FENCE_BEACON_KEY] = "0.0"
            exits = []
            kv = retry.FencedKV(fake, rank=0, job_epoch=0,
                                generation=0, lease_s=5.0,
                                exit_fn=exits.append)
            for _ in range(4):
                fc.t += 3.0   # cumulative silence would breach 5s
                with pytest.raises(KeyError):
                    kv.key_value_try_get("missing")
            assert exits == []
            assert kv.lease_remaining() > 0.0
        finally:
            core_clock.install(None)

    def test_journal_rides_write_path(self, tmp_path):
        from horovod_tpu.core.journal import KeyJournal

        fake = FlakyKV()
        journal = KeyJournal(str(tmp_path), rank=0)
        _, kv, _ = self._fenced(fake, journal=journal)
        kv.add_journal_prefix("dur/")
        kv.key_value_set("dur/vote/0", "7")
        kv.key_value_set("ephemeral/x", "1")   # not a durable prefix
        assert journal.entries() == {"dur/vote/0": "7"}
        kv.key_value_delete("dur/vote/0")
        assert journal.entries() == {}
        # replay into a fresh store: only live entries come back
        kv.key_value_set("dur/vote/0", "9")
        fresh = FlakyKV()
        reloaded = KeyJournal(str(tmp_path), rank=0)
        assert reloaded.replay(fresh) == 1
        assert fresh.d["dur/vote/0"] == "9"

    def test_fenced_kv_factory_idempotent_and_gated(self, monkeypatch):
        fake = FlakyKV()
        kv = retry.fenced_kv(fake, rank=0)
        assert isinstance(kv, retry.FencedKV)
        assert retry.fenced_kv(kv) is kv
        assert retry.fenced_kv(None) is None
        # a plain ResilientKV is re-wrapped around its inner client
        plain = retry.resilient_kv(FlakyKV(), rank=0)
        rewrapped = retry.fenced_kv(plain, rank=0)
        assert isinstance(rewrapped, retry.FencedKV)
        assert rewrapped._kv is plain._kv
        # escape hatch: fencing disabled falls back to ResilientKV
        monkeypatch.setenv("HVTPU_KV_FENCE_DISABLE", "1")
        fallback = retry.fenced_kv(FlakyKV(), rank=0)
        assert isinstance(fallback, retry.ResilientKV)
        assert not isinstance(fallback, retry.FencedKV)

    def test_zombie_rejection_exactly_once(self):
        """A writer frozen across a restart (generation bump) has every
        post-thaw write rejected by readers and fences on its first
        beacon re-check — DELIVER accounting stays exactly-once."""
        fake = FlakyKV()
        _, zombie, z_exits = self._fenced(fake, rank=0, generation=0)
        delivered = []
        zombie.key_value_set("q/1", "sample-1")
        # restart happens while the zombie is frozen
        _, live, _ = self._fenced(fake, rank=0, generation=1)
        live.key_value_set("q/1", "sample-1")    # re-delivered by gen 1
        live.key_value_set("q/2", "sample-2")
        # the zombie thaws mid-write burst: a dropped/failed op forces
        # the beacon re-check, so it fences BEFORE the write lands
        zombie._recheck = True
        with pytest.raises(retry.FencedError):
            zombie.key_value_set("q/3", "stale-sample")
        assert z_exits == [retry.FENCE_EXIT_CODE]
        assert "q/3" not in fake.d
        for k, v in live.key_value_dir_get("q/"):
            delivered.append(v)
        assert sorted(delivered) == ["sample-1", "sample-2"]
