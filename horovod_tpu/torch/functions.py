"""State-fanout helpers for torch models (parity:
horovod/torch/functions.py ``broadcast_parameters`` /
``broadcast_optimizer_state`` / ``broadcast_object``).
"""

from __future__ import annotations

from typing import Any

import numpy as np
import torch

import horovod_tpu as _hvt

from . import mpi_ops


def broadcast_parameters(params, root_rank: int = 0, process_set=None):
    """Broadcast a ``model.state_dict()`` or ``named_parameters`` from
    ``root_rank`` in place.

    All contiguous tensors ride ONE fused byte buffer: the native
    thread pool packs them in parallel (parity: FusionBufferManager +
    thread_pool.cc's parallel MemcpyInFusionBuffer), a single broadcast
    moves the bytes, and the pool scatters straight back into each
    parameter's storage.  Non-contiguous tensors take the per-tensor
    path.
    """
    from ..native import core as native_core

    if hasattr(params, "items"):
        items = list(params.items())
    else:
        items = list(params)
    items = [(n, p) for n, p in items
             if p is not None and torch.is_tensor(p)]

    fused, single = [], []
    for name, p in items:
        if p.is_contiguous() and p.device.type == "cpu":
            fused.append((name, p))
        else:
            single.append((name, p))
    if len(fused) == 1:
        single += fused
        fused = []

    if fused:
        # byte views alias each tensor's storage -> scatter lands the
        # broadcast result directly in the parameters, no per-tensor
        # copies
        views = [
            p.detach().view(-1).view(torch.uint8).numpy()
            for _, p in fused
        ]
        total = sum(v.nbytes for v in views)
        buf = np.empty(total, np.uint8)
        native_core.parallel_gather(
            memoryview(buf), [memoryview(v) for v in views]
        )
        out = mpi_ops.broadcast(
            torch.from_numpy(buf), root_rank=root_rank,
            name=f"bp.fused.{len(fused)}.{total}",
            process_set=process_set,
        )
        out_np = out.numpy()
        native_core.parallel_scatter(
            memoryview(out_np), [memoryview(v) for v in views]
        )
    for name, p in single:
        mpi_ops.broadcast_(p, root_rank=root_rank, name=f"bp.{name}",
                           process_set=process_set)


def broadcast_optimizer_state(optimizer, root_rank: int = 0,
                              process_set=None):
    """Broadcast a torch optimizer's state (exp_avg, momentum buffers,
    step counters, ...) from ``root_rank``.

    The reference reconstructs missing state by running a zero-grad
    step first (horovod/torch/functions.py); we do the same so newly
    initialized workers have state entries to receive into.
    """
    if len(optimizer.state) == 0:
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = torch.zeros_like(p)
        try:
            optimizer.step()
        except Exception:
            pass
    state = optimizer.state_dict()
    new_state = broadcast_object(state, root_rank=root_rank,
                                 process_set=process_set)
    if _hvt.rank() != root_rank:
        optimizer.load_state_dict(new_state)


def broadcast_object(obj: Any, root_rank: int = 0, name: str = None,
                     process_set=None) -> Any:
    """Pickle-broadcast an arbitrary object (parity: hvd.broadcast_object).
    Torch tensors pickle fine, so this delegates to the engine's
    size-then-payload wire protocol."""
    del name
    return _hvt.broadcast_object(obj, root_rank=root_rank,
                                 process_set=process_set)


def allgather_object(obj: Any, process_set=None):
    return _hvt.allgather_object(obj, process_set=process_set)
