"""Hybrid-parallel transformer correctness on the 8-device CPU mesh.

Gold test: the full dp×pp×tp(+sp,+ep) sharded training step must match
a single-device dense execution of the same model to tolerance — loss
AND gradients — proving every collective (all_gather, psum_scatter,
psum, ppermute pipeline, all_to_all MoE) and every transpose rule in
the sharded path is exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu import parallel as par
from horovod_tpu.models import transformer as tfm


def tiny_cfg(**kw):
    base = dict(
        vocab_size=64,
        d_model=32,
        n_heads=4,
        n_layers=4,
        d_ff=64,
        max_seq=32,
        dtype=jnp.float32,
        num_microbatches=2,
    )
    base.update(kw)
    return tfm.TransformerConfig(**base)


def single_device_layout():
    """1-device 'mesh' with every axis size 1 — runs the same code path
    dense, giving the ground-truth reference."""
    return par.make_layout(jax.devices()[:1], dp=1, tp=1, pp=1)


def tokens_for(cfg, batch):
    rng = np.random.RandomState(0)
    return jnp.asarray(
        rng.randint(0, cfg.vocab_size, size=(batch, 17)), jnp.int32
    )


BATCH = 16  # divisible by dp×microbatches for every layout under test


class TestHybridMatchesDense:
    @pytest.mark.parametrize(
        "dp,tp,pp", [(2, 2, 2), (8, 1, 1), (1, 4, 2), (2, 4, 1)]
    )
    def test_loss_and_grads(self, dp, tp, pp):
        cfg = tiny_cfg()
        layout = par.make_layout(jax.devices(), dp=dp, tp=tp, pp=pp)
        ref_layout = single_device_layout()

        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        toks = tokens_for(cfg, batch=BATCH)

        loss_sharded = tfm.make_loss_fn(cfg, layout)
        loss_dense = tfm.make_loss_fn(cfg, ref_layout)

        l_s, g_s = jax.jit(jax.value_and_grad(loss_sharded))(params, toks)
        l_d, g_d = jax.jit(jax.value_and_grad(loss_dense))(params, toks)

        np.testing.assert_allclose(float(l_s), float(l_d), rtol=1e-5)
        flat_s = jax.tree_util.tree_leaves_with_path(g_s)
        flat_d = jax.tree_util.tree_leaves(g_d)
        for (path, a), b in zip(flat_s, flat_d):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}"
                        f" (dp={dp} tp={tp} pp={pp})",
            )

    def test_ring_mode_matches_megatron_mode(self):
        """Dedicated-sp ring attention path vs megatron_sp path — same
        math, different comm schedule."""
        toks = tokens_for(tiny_cfg(), batch=BATCH)
        params = tfm.init_params(tiny_cfg(), jax.random.PRNGKey(0))

        cfg_m = tiny_cfg(attn_mode="megatron_sp")
        lay_m = par.make_layout(jax.devices(), dp=2, tp=2, pp=2)
        l_m = jax.jit(tfm.make_loss_fn(cfg_m, lay_m))(params, toks)

        cfg_r = tiny_cfg(attn_mode="ring")
        lay_r = par.make_layout(jax.devices(), dp=2, tp=2, sp=2, pp=1)
        l_r = jax.jit(tfm.make_loss_fn(cfg_r, lay_r))(params, toks)

        cfg_u = tiny_cfg(attn_mode="ulysses")
        l_u = jax.jit(tfm.make_loss_fn(cfg_u, lay_r))(params, toks)

        np.testing.assert_allclose(float(l_m), float(l_r), rtol=1e-5)
        np.testing.assert_allclose(float(l_m), float(l_u), rtol=1e-5)

    def test_moe_runs_and_trains(self):
        """Switch-MoE over ep (shared with dp): loss finite and
        decreasing over a few steps on the full hybrid mesh."""
        cfg = tiny_cfg(n_experts=4, n_layers=2, num_microbatches=2)
        layout = par.make_layout(jax.devices(), dp=2, tp=2, pp=2)
        params = tfm.init_params(cfg, jax.random.PRNGKey(1))
        toks = tokens_for(cfg, batch=BATCH)

        tx = optax.adam(1e-2)
        step = tfm.make_train_step(cfg, layout, tx)
        opt_state = tx.init(params)

        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, toks)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_grads_match_dense(self):
        """MoE expert grads across ep==dp sharding vs dense 1-device."""
        # aux_loss_weight=0: the aux value legitimately differs between
        # routing-group layouts (per-member vs global groups), so exact
        # grad comparison is only meaningful for the main loss.
        cfg = tiny_cfg(n_experts=4, n_layers=2, num_microbatches=1,
                       capacity_factor=8.0, aux_loss_weight=0.0)
        layout = par.make_layout(jax.devices(), dp=2, tp=2, pp=2)
        ref_layout = single_device_layout()
        params = tfm.init_params(cfg, jax.random.PRNGKey(2))
        toks = tokens_for(cfg, batch=BATCH)

        g_s = jax.jit(jax.grad(tfm.make_loss_fn(cfg, layout)))(params, toks)
        g_d = jax.jit(jax.grad(tfm.make_loss_fn(cfg, ref_layout)))(
            params, toks)
        flat_s = jax.tree_util.tree_leaves_with_path(g_s)
        flat_d = jax.tree_util.tree_leaves(g_d)
        for (path, a), b in zip(flat_s, flat_d):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
            )


class TestTrainStep:
    def test_loss_decreases_full_hybrid(self):
        cfg = tiny_cfg()
        layout = par.make_layout(jax.devices(), dp=2, tp=2, pp=2)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        toks = tokens_for(cfg, batch=BATCH)
        tx = optax.adam(1e-2)
        step = tfm.make_train_step(cfg, layout, tx)
        opt_state = tx.init(params)
        first = last = None
        for i in range(8):
            params, opt_state, loss = step(params, opt_state, toks)
            if i == 0:
                first = float(loss)
            last = float(loss)
        assert last < first * 0.9, (first, last)
